"""Static pricing of sharded programs: collective bytes from a plan.

The sharding sibling of the roofline cost model: given a program and a
:class:`paddle_tpu.parallel.ShardingPlan`, estimate the per-device wire
bytes GSPMD's inserted collectives move per step — BEFORE lowering,
from the same inferred shapes the memory analyzer uses. Three families,
priced with the standard ring-algorithm factors:

- **grad all-reduce** (data parallelism): every trainable parameter
  replicated across the ``dp`` axis gets its gradient psummed — ring
  all-reduce moves ``2 (n-1)/n x shard_bytes`` per device;
- **tp all-reduce** (Megatron tensor parallelism): an op contracting
  against a weight sharded on its OUTPUT dim produces partial sums the
  consumer needs combined — one all-reduce of the output activation per
  sharded layer, forward, mirrored in the backward when the program
  trains;
- **expert all-to-all**: ops reading ``[E, ...]`` expert-major tensors
  sharded on ``ep`` exchange their tokens — ``(n-1)/n x activation``
  bytes each way.

These are analytic approximations in the cost model's ~20% honesty
class (GSPMD may fuse, reduce-scatter, or elide); ``bench_sharding``
records estimate-vs-measured drift per release so the model cannot rot
silently.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

from ..core.program import Program
from ..core.scope import Scope
from ..parallel.plan import spec_axes

# v5e-class ICI: one-way per-chip bandwidth along a torus axis (the
# scaling-book planning number; DCN-crossing axes are ~10x slower and
# out of scope for this single-slice estimate).
V5E_ICI_BW = 9.0e10

_GRAD_OPS = ("grad", "grad_custom", "grad_seg")

# ops that CONTRACT against their weight: only these turn a sharded
# weight dim into partial sums needing an all-reduce. A bias add against
# a sharded bias keeps the output sharded — no collective.
_CONTRACT_OPS = ("mul", "matmul", "fc", "conv2d", "depthwise_conv2d",
                 "conv1x1_bn_act", "embedding", "lookup_table",
                 "fused_head_cross_entropy", "pipelined_transformer_stack")


def _contract_like(op) -> bool:
    if op.type in _CONTRACT_OPS:
        return True
    if op.type in _GRAD_OPS:
        return op.attrs.get("fwd_type") in _CONTRACT_OPS
    return False


@dataclasses.dataclass
class CollectiveRow:
    """One priced collective: what moves, over which axis, how much."""

    kind: str    # "grad_allreduce" | "tp_allreduce" | "ep_all2all"
    axis: str
    name: str    # parameter name or "op #i <type>" label
    bytes: float  # per-device wire bytes per step (fwd+bwd where priced)

    def format(self) -> str:
        return (f"{self.bytes / 1e6:>10.2f} MB  {self.kind:<15} "
                f"over {self.axis!r}  {self.name}")


@dataclasses.dataclass
class ShardingCost:
    """Result of :func:`estimate_collectives`."""

    mesh_axes: Dict[str, int]
    rows: List[CollectiveRow]
    per_device_state_bytes: float = 0.0
    replicated_state_bytes: float = 0.0

    @property
    def total_bytes(self) -> float:
        return sum(r.bytes for r in self.rows)

    def bytes_by_kind(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for r in self.rows:
            out[r.kind] = out.get(r.kind, 0.0) + r.bytes
        return out

    def time_seconds(self, ici_bw: float = V5E_ICI_BW) -> float:
        """Lower-bound wire time assuming no compute overlap (XLA
        overlaps aggressively; this bounds the exposed cost)."""
        return self.total_bytes / ici_bw

    def format_report(self, top_n: int = 8) -> str:
        axes = "x".join(f"{a}={s}" for a, s in self.mesh_axes.items())
        lines = [
            f"collectives over mesh [{axes}]: "
            f"{self.total_bytes / 1e6:.2f} MB/device/step "
            f"(~{self.time_seconds() * 1e3:.3f} ms wire floor on v5e ICI)",
        ]
        for kind, b in sorted(self.bytes_by_kind().items(),
                              key=lambda kv: -kv[1]):
            lines.append(f"  {kind}: {b / 1e6:.2f} MB")
        for r in sorted(self.rows, key=lambda r: -r.bytes)[:top_n]:
            lines.append("  " + r.format())
        return "\n".join(lines)


def _nbytes(sds) -> float:
    from . import costmodel

    return costmodel._nbytes(sds)


def _leaf_shape(sds):
    from . import costmodel

    leaves = costmodel._leaves(sds)
    return tuple(leaves[0].shape) if leaves else ()


def _shard_div(spec, axis_sizes: Dict[str, int]) -> int:
    div = 1
    for ax in spec_axes(spec):
        div *= axis_sizes.get(ax, 1)
    return div


def estimate_collectives(program: Program, feed_names: Sequence[str] = (),
                         fetch_names: Sequence[str] = (),
                         plan=None, scope: Optional[Scope] = None,
                         batch_size: int = 1,
                         types: Optional[dict] = None) -> ShardingCost:
    """Price the per-step collectives of ``program`` under ``plan``.

    ``types`` (name -> concrete ShapeDtypeStruct) lets the memory
    analyzer share its inferred shapes; omitted, the checker runs here.
    """
    from .checker import infer_program
    from .memory import _concrete, _lookup_var

    plan = plan if plan is not None \
        else getattr(program, "sharding_plan", None)
    if plan is None:
        raise ValueError("estimate_collectives needs a ShardingPlan "
                         "(argument or ShardProgram-annotated program)")
    if types is None:
        analysis = infer_program(program, feed_names, fetch_names,
                                 scope=scope, annotate=False)
        types = {name: _concrete(sds, batch_size)
                 for name, sds in analysis.types.items()}
    block = program.global_block
    axis_sizes = plan.mesh_axes()
    data_axis = plan.data_axis
    n_dp = axis_sizes.get(data_axis, 1) if data_axis else 1
    training = any(op.type in _GRAD_OPS for op in block.ops)
    rows: List[CollectiveRow] = []

    # ---- per-parameter specs (annotation first, plan rules second) ----
    def state_spec(name: str):
        v = _lookup_var(block, name)
        ann = getattr(v, "sharding", None) if v is not None else None
        if ann is not None:
            return ann
        sds = types.get(name)
        shape = _leaf_shape(sds) if sds is not None else None
        if shape is None and v is not None:
            shape = v.shape
        ndim = len(shape) if shape is not None else 0
        return plan.spec_for_state(name, ndim, shape=shape)

    per_dev_state = 0.0
    replicated_state = 0.0
    seen: set = set()
    for b in program.blocks:
        for v in b.vars.values():
            if not v.persistable or v.name in seen or v.name not in types:
                continue
            seen.add(v.name)
            spec = state_spec(v.name)
            full = _nbytes(types[v.name])
            div = _shard_div(spec, axis_sizes)
            per_dev_state += full / div
            if div == 1:
                replicated_state += full
            # grad all-reduce: a trainable parameter replicated over dp
            # psums its gradient every step (the MultiGradientMachine /
            # sync-pserver exchange, in-graph)
            if (training and n_dp > 1 and v.is_parameter
                    and getattr(v, "trainable", True)
                    and data_axis not in spec_axes(spec)):
                shard_bytes = full / div
                rows.append(CollectiveRow(
                    kind="grad_allreduce", axis=data_axis, name=v.name,
                    bytes=2.0 * (n_dp - 1) / n_dp * shard_bytes))

    # ---- per-op model-parallel collectives ----------------------------
    def activation_div(name: str) -> int:
        """dp sharding GSPMD propagates onto a batch-led activation."""
        from ..core.program import BATCH_DIM_SENTINEL

        if n_dp <= 1:
            return 1
        sds = types.get(name)
        shape = _leaf_shape(sds) if sds is not None else ()
        if shape and (shape[0] == batch_size
                      or (batch_size > 1 and shape[0] % batch_size == 0)):
            return n_dp
        return 1

    for i, op in enumerate(block.ops):
        weight_specs = []
        for name in op.input_names():
            v = _lookup_var(block, name)
            if v is None or not v.persistable:
                continue
            spec = state_spec(name)
            model_axes = [ax for ax in spec_axes(spec) if ax != data_axis]
            if model_axes:
                weight_specs.append((name, spec, model_axes))
        if not weight_specs:
            continue
        outs = [n for n in op.output_names() if n in types]
        if not outs:
            continue
        out_name = outs[0]
        out_bytes = _nbytes(types[out_name]) / activation_div(out_name)
        for name, spec, model_axes in weight_specs:
            entries = tuple(spec)
            for ax in model_axes:
                n_ax = axis_sizes.get(ax, 1)
                if n_ax <= 1:
                    continue
                last = entries[-1] if entries else None
                last_axes = (last if isinstance(last, tuple)
                             else (last,)) if last is not None else ()
                first = entries[0] if entries else None
                first_axes = (first if isinstance(first, tuple)
                              else (first,)) if first is not None else ()
                # forward AND backward ops each contribute their own row
                # (a program with grad ops walks both), so no x2 here
                if ".expert_" in name and ax in first_axes:
                    rows.append(CollectiveRow(
                        kind="ep_all2all", axis=ax,
                        name=f"op #{i} {op.type} ({name})",
                        bytes=(n_ax - 1) / n_ax * out_bytes))
                elif ax in last_axes and _contract_like(op):
                    # column-parallel output dim: when the consumer
                    # contracts over it the partial sums combine — one
                    # ring all-reduce of the full output activation
                    # (2 (n-1)/n x D wire bytes/device)
                    rows.append(CollectiveRow(
                        kind="tp_allreduce", axis=ax,
                        name=f"op #{i} {op.type} ({name})",
                        bytes=2.0 * (n_ax - 1) / n_ax * out_bytes))

    return ShardingCost(mesh_axes=dict(axis_sizes), rows=rows,
                        per_device_state_bytes=per_dev_state,
                        replicated_state_bytes=replicated_state)

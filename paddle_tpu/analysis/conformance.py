"""Op-registry conformance audit.

A statically-checkable metadata contract for every registered op: when a
new kernel is registered inconsistently (an ``optional_inputs`` slot the
kernel never reads, a ``needs_rng`` predicate that isn't callable-safe,
``grad_fn_is_optimization`` without a ``grad_fn``), the audit — run by
``tests/test_registry_conformance.py`` and ``tools/proglint.py
--audit`` — fails with the op named, instead of the inconsistency
surfacing as a runtime crash in whatever program first exercises it.
"""
from __future__ import annotations

import inspect
from typing import List, Optional

from ..core.registry import OpDef, get_op, registered_ops
from .lint import ERROR, LintIssue


def _kernel_source(opdef: OpDef) -> Optional[str]:
    try:
        return inspect.getsource(opdef.fn)
    except (OSError, TypeError):
        return None


def _accepts_rng(opdef: OpDef) -> bool:
    try:
        sig = inspect.signature(opdef.fn)
    except (ValueError, TypeError):
        return True  # unsignaturable callables: give the benefit of doubt
    params = sig.parameters
    return "rng" in params or any(
        p.kind is inspect.Parameter.VAR_KEYWORD for p in params.values())


def _slot_mentioned(source: Optional[str], slot: str) -> bool:
    """Kernels address slots as string literals (``ins["Bias"]``,
    ``maybe(ins, "Bias")``); a declared slot whose name never appears in
    the kernel source is a stale declaration."""
    if source is None:
        return True  # source unavailable (C-accelerated, exec'd): skip
    return f'"{slot}"' in source or f"'{slot}'" in source


def _op_issue(op_type: str, severity: str, message: str) -> LintIssue:
    return LintIssue(rule="registry-conformance", severity=severity,
                     message=f"op {op_type!r}: {message}", op_type=op_type)


def audit_op(op_type: str) -> List[LintIssue]:
    """Audit one op's registry metadata; returns issues (empty = clean)."""
    opdef = get_op(op_type)
    issues: List[LintIssue] = []

    for field in ("optional_inputs", "stop_gradient_inputs"):
        slots = getattr(opdef, field)
        if not isinstance(slots, tuple):
            issues.append(_op_issue(
                op_type, ERROR, f"{field} must be a tuple, got "
                                f"{type(slots).__name__}"))
            continue
        for slot in slots:
            if not isinstance(slot, str) or not slot:
                issues.append(_op_issue(
                    op_type, ERROR,
                    f"{field} entry {slot!r} is not a slot name"))

    # needs_rng: strictly False, strictly True, or a predicate over attrs
    nr = opdef.needs_rng
    if not isinstance(nr, bool):
        if not callable(nr):
            issues.append(_op_issue(
                op_type, ERROR,
                f"needs_rng must be a bool or a predicate over attrs, "
                f"got {type(nr).__name__}"))
        else:
            try:
                verdict = nr({})
                bool(verdict)
            except Exception as exc:
                issues.append(_op_issue(
                    op_type, ERROR,
                    f"needs_rng predicate must accept an attrs dict and "
                    f"return a truth value; calling it with {{}} raised "
                    f"{type(exc).__name__}: {exc}"))

    if opdef.grad_fn_is_optimization and opdef.grad_fn is None:
        issues.append(_op_issue(
            op_type, ERROR,
            "grad_fn_is_optimization=True requires a grad_fn (the flag "
            "asserts vjp-of-forward is still valid ALONGSIDE a custom "
            "grad — with no grad_fn it is meaningless)"))

    # cost-model coverage contract (applies to special ops too): every
    # op carries an analytical cost handler (costmodel.register_cost) or
    # an explicit cost_exempt marker — the roofline/memory plane must
    # never meet an op it silently cannot price
    from . import costmodel

    costmodel.ensure_registered()
    if opdef.cost_fn is None and not opdef.cost_exempt:
        issues.append(_op_issue(
            op_type, ERROR,
            "no cost-model handler registered and not cost_exempt: add "
            "a handler via analysis.costmodel.register_cost (FLOPs + "
            "HBM bytes from the abstract input/output shapes) or mark "
            "it analysis.costmodel.cost_exempt with a reason"))
    if opdef.cost_fn is not None and not callable(opdef.cost_fn):
        issues.append(_op_issue(op_type, ERROR, "cost_fn is not callable"))

    if opdef.special:
        return issues  # executor-trace calling convention: nothing below
    # applies (special kernels take executor/env/op kwargs)

    if (opdef.needs_rng is not False) and not _accepts_rng(opdef):
        issues.append(_op_issue(
            op_type, ERROR,
            "needs_rng is not strictly False, so the kernel must accept "
            "an ``rng`` keyword (None when this instance draws nothing)"))

    source = _kernel_source(opdef)
    for field in ("optional_inputs", "stop_gradient_inputs"):
        slots = getattr(opdef, field)
        if not isinstance(slots, tuple):
            continue
        for slot in slots:
            if isinstance(slot, str) and not _slot_mentioned(source, slot):
                issues.append(_op_issue(
                    op_type, ERROR,
                    f"{field} declares slot {slot!r} but the kernel "
                    f"source never references it — stale or misspelled "
                    f"slot declaration"))
    if opdef.grad_fn is not None and not callable(opdef.grad_fn):
        issues.append(_op_issue(op_type, ERROR, "grad_fn is not callable"))
    return issues


def audit_op_registry() -> List[LintIssue]:
    """Audit every registered op. Returns all findings; a clean registry
    returns []."""
    issues: List[LintIssue] = []
    for op_type in registered_ops():
        issues.extend(audit_op(op_type))
    return issues

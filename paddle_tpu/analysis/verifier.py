"""Program verifier: structural invariants every well-formed program obeys.

The checks that need no abstract evaluation — they walk the op list once
and catch the failure modes transpiler rewrites historically introduce:
dangling inputs after a dropped producer, unknown op types after a
rename, duplicate writes, dead outputs left behind by a partial rewrite,
violated optional-input contracts, nondeterministic RNG draws, and
async/donation hazards (fetching a state variable the executor donates
to XLA on ``run_async``). Each invariant is a registered
:class:`~paddle_tpu.analysis.lint.LintRule`, so ``tools/proglint.py``
and custom rule sets compose them freely.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..core.enforce import EnforceError
from ..core.program import Block, Program
from ..core.registry import get_op, has_op, op_uses_rng
from ..core.scope import Scope
from .lint import (ERROR, WARNING, LintContext, LintIssue, LintRule,
                   register_rule, run_lint)


class ProgramVerifyError(EnforceError):
    """A program violates structural invariants. ``issues`` carries every
    error-severity finding."""

    def __init__(self, issues: Sequence[LintIssue]):
        self.issues = list(issues)
        lines = "\n".join("  " + i.format() for i in self.issues)
        super().__init__(
            f"program verification failed with {len(self.issues)} "
            f"error(s):\n{lines}")


def _issue(rule: str, severity: str, block: Block, op_index, op, message,
           slot=None, var=None) -> LintIssue:
    return LintIssue(
        rule=rule, severity=severity, message=message, block_idx=block.idx,
        op_index=op_index, op_type=op.type if op is not None else None,
        callsite=op.attrs.get("_callsite") if op is not None else None,
        slot=slot, var=var)


def _lookup_var(block: Block, name: str):
    b = block
    while b is not None:
        if name in b.vars:
            return b.vars[name]
        b = b.parent
    return None


def _frontier(block: Block, ctx: LintContext) -> Set[str]:
    """Names available before the block's first op runs: feeds, scope
    state, declared persistable/data vars — plus, for sub-blocks,
    everything any ancestor block produces (a sub-block executes at its
    parent op's position; order across blocks is not re-checked here)."""
    avail = set(ctx.feed_names)
    if ctx.scope is not None:
        s = ctx.scope
        while s is not None:
            avail.update(s.keys())
            s = s.parent
    b = block
    while b is not None:
        for name, v in b.vars.items():
            if v.persistable or v.is_data:
                avail.add(name)
        if b is not block:
            for op in b.ops:
                avail.update(op.output_names())
        b = b.parent
    return avail


# --------------------------------------------------------------------------
@register_rule
class UnknownOpRule(LintRule):
    """Every op type must resolve in the kernel registry."""

    name = "unknown-op"

    def check(self, program, ctx):
        for block in program.blocks:
            for i, op in enumerate(block.ops):
                if not has_op(op.type):
                    yield _issue(self.name, ERROR, block, i, op,
                                 "op type is not registered")


# --------------------------------------------------------------------------
@register_rule
class UseBeforeDefRule(LintRule):
    """Every input must be produced by an earlier op, fed, persistable,
    or resident in the scope — the executor's exact data-flow contract
    (core/executor.py _compile). The canonical broken-rewrite symptom: a
    pass drops a producer but leaves the consumers."""

    name = "use-before-def"

    def check(self, program, ctx):
        for block in program.blocks:
            avail = _frontier(block, ctx)
            for i, op in enumerate(block.ops):
                for slot, names in op.inputs.items():
                    for name in names:
                        if name in avail:
                            continue
                        v = _lookup_var(block, name)
                        if v is not None:
                            yield _issue(
                                self.name, ERROR, block, i, op,
                                f"input {slot}={name!r} is declared but "
                                f"produced by no earlier op and is "
                                f"neither fed, persistable, nor "
                                f"scope-resident", slot=slot, var=name)
                        elif ctx.scope is not None:
                            yield _issue(
                                self.name, ERROR, block, i, op,
                                f"input {slot}={name!r} is not declared "
                                f"in the program and not resident in the "
                                f"scope", slot=slot, var=name)
                        else:
                            yield _issue(
                                self.name, WARNING, block, i, op,
                                f"input {slot}={name!r} is not declared "
                                f"in the program; without a scope its "
                                f"availability cannot be proven",
                                slot=slot, var=name)
                        avail.add(name)  # report each name once
                avail.update(op.output_names())


# --------------------------------------------------------------------------
@register_rule
class DuplicateOutputRule(LintRule):
    """One op writing the same name through two slots is a rewrite bug
    (aliased state across DIFFERENT ops — batch_norm's MeanOut onto Mean
    — is legal and untouched)."""

    name = "duplicate-output"

    def check(self, program, ctx):
        for block in program.blocks:
            for i, op in enumerate(block.ops):
                seen: Dict[str, str] = {}
                for slot, names in op.outputs.items():
                    for name in names:
                        if name in seen:
                            yield _issue(
                                self.name, ERROR, block, i, op,
                                f"output {name!r} is written by both "
                                f"slot {seen[name]!r} and slot {slot!r}",
                                slot=slot, var=name)
                        else:
                            seen[name] = slot


# --------------------------------------------------------------------------
@register_rule
class DeadOutputRule(LintRule):
    """An op NONE of whose outputs is read, fetched, or state does pure
    dead work every step — DCE fodder a rewrite left behind. Fires per
    op, not per output: an unconsumed auxiliary slot next to a live
    primary (batch_norm's SavedMean, layer_norm's Mean) costs nothing —
    the kernel computes it either way. Warning severity: dead ops
    execute correctly."""

    name = "dead-output"

    def check(self, program, ctx):
        from ..core.program import GRAD_SUFFIX

        fetches = set(ctx.fetch_names)
        consumed: Set[str] = set()
        for block in program.blocks:
            for op in block.ops:
                consumed.update(op.input_names())

        def live(block, name):
            if name in consumed or name in fetches:
                return True
            if name.endswith(GRAD_SUFFIX):
                # canonical @GRAD assigns are the fetchable gradient
                # API surface, not dead work
                return True
            v = _lookup_var(block, name)
            if v is not None and v.persistable:
                return True
            # unfetched state write (KV caches)
            return ctx.scope is not None and ctx.scope.has(name)

        for block in program.blocks:
            for i, op in enumerate(block.ops):
                names = op.output_names()
                if not names:
                    continue
                if any(live(block, n) for n in names):
                    continue
                yield _issue(
                    self.name, WARNING, block, i, op,
                    f"no output of this op is consumed, fetched, or "
                    f"persistable state (outputs: "
                    f"{names[:4]}{'...' if len(names) > 4 else ''})")


# --------------------------------------------------------------------------
@register_rule
class OptionalInputContractRule(LintRule):
    """An empty input slot is only legal when the op declares it in
    ``optional_inputs`` — anything else would make the kernel see a slot
    it requires vanish (the executor silently drops empty slots)."""

    name = "optional-input-contract"

    def check(self, program, ctx):
        for block in program.blocks:
            for i, op in enumerate(block.ops):
                if not has_op(op.type):
                    continue  # unknown-op already fires
                opdef = get_op(op.type)
                if opdef.special:
                    continue
                for slot, names in op.inputs.items():
                    if not names and slot not in opdef.optional_inputs:
                        yield _issue(
                            self.name, WARNING, block, i, op,
                            f"input slot {slot!r} is present but empty "
                            f"and not declared optional "
                            f"(optional_inputs="
                            f"{list(opdef.optional_inputs)})", slot=slot)


# --------------------------------------------------------------------------
@register_rule
class RngDeterminismRule(LintRule):
    """Ops drawing randomness in a program with no ``random_seed`` fall
    back to the process-global ``--seed`` flag: reproducible only if
    every launcher pins it. Lint so training runs meant to be replayable
    plumb an explicit seed."""

    name = "rng-no-seed"

    def check(self, program, ctx):
        if program.random_seed is not None:
            return
        for block in program.blocks:
            for i, op in enumerate(block.ops):
                if not has_op(op.type):
                    continue
                if op_uses_rng(get_op(op.type), op.attrs):
                    yield _issue(
                        self.name, WARNING, block, i, op,
                        "op draws randomness but the program sets no "
                        "random_seed (falls back to the global --seed "
                        "flag)")
                    return  # one finding per program is enough


# --------------------------------------------------------------------------
def written_state_names(program: Program,
                        scope: Optional[Scope] = None) -> Set[str]:
    """Names the executor writes back to the scope after a run — declared
    persistable outputs plus outputs of names resident in ``scope``.
    These are DONATED to XLA on ``run_async`` dispatch (their previous
    buffers are invalidated in flight)."""
    written: Set[str] = set()
    for block in program.blocks:
        for op in block.ops:
            for name in op.output_names():
                v = _lookup_var(block, name)
                if (v is not None and v.persistable) or (
                        scope is not None and scope.has(name)):
                    written.add(name)
    return written


@register_rule
class DonatedFetchRule(LintRule):
    """Fetching a variable the run also writes back as state is an async
    hazard: ``run_async`` donates the written-back buffer to the next
    dispatch, so the fetched handle may alias memory XLA reuses. The
    sync path is safe; flag it so async pipelines don't inherit it."""

    name = "fetch-donated-state"

    def check(self, program, ctx):
        written = written_state_names(program, ctx.scope)
        for name in ctx.fetch_names:
            if name in written:
                yield LintIssue(
                    rule=self.name, severity=WARNING,
                    message=f"fetch {name!r} is also written-back state: "
                            f"run_async donates its buffer to the next "
                            f"dispatch (read the fetch via "
                            f"handle.result() before dispatching again)",
                    var=name)


@register_rule
class FetchProducedRule(LintRule):
    """Every fetch target must be produced by some op, persistable, or
    scope-resident."""

    name = "fetch-never-produced"

    def check(self, program, ctx):
        produced: Set[str] = set()
        for block in program.blocks:
            for op in block.ops:
                produced.update(op.output_names())
        for name in ctx.fetch_names:
            if name in produced:
                continue
            if ctx.scope is not None and ctx.scope.has(name):
                continue
            v = _lookup_var(program.global_block, name)
            if v is not None and v.persistable:
                continue
            yield LintIssue(
                rule=self.name, severity=ERROR,
                message=f"fetch {name!r} is never produced by any op and "
                        f"is not persistable/scope state", var=name)


# --------------------------------------------------------------------------
def check_async_overlap(
        runs: Sequence[Tuple[Program, Sequence[str], Sequence[str]]],
        scope: Optional[Scope] = None) -> List[LintIssue]:
    """Async hazard check across programs meant to be in flight together
    (``Executor.run_async`` chains): two dispatches whose write-back
    state sets overlap race on donated buffers unless serialized.

    ``runs`` is ``[(program, feed_names, fetch_names), ...]``; returns
    one warning per overlapping pair.
    """
    issues: List[LintIssue] = []
    writes = [written_state_names(p, scope) for p, _, _ in runs]
    for a in range(len(runs)):
        for b in range(a + 1, len(runs)):
            overlap = writes[a] & writes[b]
            if overlap:
                names = ", ".join(repr(n) for n in sorted(overlap)[:6])
                issues.append(LintIssue(
                    rule="overlapping-state-writes", severity=WARNING,
                    message=f"programs #{a} and #{b} both write state "
                            f"{{{names}}}: overlapping run_async "
                            f"dispatches race on donated buffers — "
                            f"serialize them or split the state"))
    return issues


# --------------------------------------------------------------------------
def verify_program(program: Program, feed_names: Sequence[str] = (),
                   fetch_names: Sequence[str] = (),
                   scope: Optional[Scope] = None,
                   rules: Optional[Sequence] = None,
                   raise_on_error: bool = True) -> List[LintIssue]:
    """Run the structural rule battery. Error-severity findings raise
    :class:`ProgramVerifyError` (unless ``raise_on_error=False``); the
    warning-severity remainder is returned."""
    issues = run_lint(program, feed_names, fetch_names, scope=scope,
                      rules=rules)
    errors = [i for i in issues if i.severity == ERROR]
    if errors and raise_on_error:
        raise ProgramVerifyError(errors)
    return issues if not raise_on_error else [
        i for i in issues if i.severity != ERROR]

"""Whole-program static shape/dtype inference.

Propagates ``jax.ShapeDtypeStruct``s from the feed/persistable frontier
through every op via the registry's ``infer_outputs`` (the kernel itself
under ``jax.eval_shape`` — one source of truth, no per-op InferShape to
drift), understanding the ``-1`` batch sentinel (program.py
BATCH_DIM_SENTINEL), optional inputs, and the executor's ``special``
feed/fetch/recompute-segment ops. Inferred shapes/dtypes are annotated
back onto the program's :class:`Variable`s, and any inconsistency —
kernel rejection or an inferred shape contradicting the declared one —
raises :class:`ProgramCheckError` naming the op index, type, user
callsite, and offending slot at BUILD time, where the reference's per-op
``InferShape`` would have fired, instead of surfacing as an opaque JAX
trace error deep inside ``jit``.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax
import numpy as np

from ..core.enforce import EnforceError, format_input_sigs
from ..core.program import BATCH_DIM_SENTINEL, Block, Operator, Program
from ..core.registry import get_op, has_op, infer_outputs
from ..core.scope import Scope
from .lint import WARNING, LintIssue


class ProgramCheckError(EnforceError):
    """A program failed whole-program shape/dtype checking. Carries the
    located context (op index/type/callsite, slot, var) as attributes so
    tools can render it structurally."""

    def __init__(self, message: str, *, block_idx: int = 0,
                 op_index: Optional[int] = None,
                 op_type: Optional[str] = None,
                 callsite: Optional[str] = None,
                 slot: Optional[str] = None, var: Optional[str] = None):
        super().__init__(message)
        self.block_idx = block_idx
        self.op_index = op_index
        self.op_type = op_type
        self.callsite = callsite
        self.slot = slot
        self.var = var


class ProgramAnalysis:
    """Result of :func:`infer_program`: every value name mapped to its
    inferred ``ShapeDtypeStruct`` (batch dims carry the sentinel), plus
    non-fatal findings (dtype drift) as :class:`LintIssue`s."""

    def __init__(self):
        self.types: Dict[str, jax.ShapeDtypeStruct] = {}
        self.issues: List[LintIssue] = []

    def shape_of(self, name: str) -> Optional[tuple]:
        """Build-convention shape (sentinel rendered back as -1)."""
        sds = self.types.get(name)
        return None if sds is None else _build_shape(sds.shape)

    def dtype_of(self, name: str):
        sds = self.types.get(name)
        return None if sds is None else sds.dtype


def _build_shape(shape) -> tuple:
    """Concrete abstract shape -> build convention (-1 batch dims)."""
    return tuple(-1 if d == BATCH_DIM_SENTINEL else int(d) for d in shape)


def _fmt_shape(shape) -> str:
    return str(_build_shape(shape))


def _op_loc(block: Block, op: Operator, op_index: int) -> str:
    site = op.attrs.get("_callsite")
    loc = f"block {block.idx} op #{op_index} {op.type!r}"
    return loc + (f" (created at {site})" if site else "")


def _sds_of_value(val) -> object:
    """ShapeDtypeStruct (tree) for a runtime value without touching the
    host: jax/numpy arrays expose shape+dtype; pytree state values
    (SelectedRows) map leaf-wise; python scalars go through numpy."""
    def leaf(a):
        shape = getattr(a, "shape", None)
        dtype = getattr(a, "dtype", None)
        if shape is None or dtype is None:
            a = np.asarray(a)
            shape, dtype = a.shape, a.dtype
        return jax.ShapeDtypeStruct(tuple(shape), dtype)

    return jax.tree_util.tree_map(leaf, val)


# --------------------------------------------------------------------------
# Special-op abstract handlers
#
# ``special`` ops are executed by the tracer with an environment, not
# called as pure kernels, so infer_outputs cannot evaluate them. Each one
# gets an abstract interpretation here; new special ops must register a
# handler or the checker rejects programs containing them.
# --------------------------------------------------------------------------
def _infer_seg_fwd(op: Operator, resolve, infer_op) -> Dict[str, list]:
    """Composite recompute-segment forward: walk its serialized seg_ops
    exactly like top-level ops, in a local environment seeded from the
    external inputs (backward.py segment_forward contract)."""
    local: Dict[str, object] = {}
    for name in op.attrs["ext_in"]:
        local[name] = resolve(name)
    for j, sop in enumerate(op.attrs["seg_ops"]):
        ins = {slot: [local[n] for n in names]
               for slot, names in sop["ins"].items() if names}
        outs = infer_op(sop["type"], sop["attrs"], ins,
                        where=f"seg_ops[{j}]")
        for slot, names in sop["outs"].items():
            for n, sds in zip(names, (outs or {}).get(slot, [])):
                local[n] = sds
    return {"O": [local[n] for n in op.attrs["all_outs"]]}


def _infer_grad_seg(op: Operator, resolve, infer_op) -> Dict[str, list]:
    """Segment backward: one input-gradient per differentiated external
    input, shaped like that input (backward.py segment_grad contract)."""
    dnames = [n for n, d in zip(op.attrs["ext_in"], op.attrs["diff"]) if d]
    return {"IG": [resolve(n) for n in dnames]}


SPECIAL_HANDLERS = {
    "seg_fwd": _infer_seg_fwd,
    "grad_seg": _infer_grad_seg,
}


# --------------------------------------------------------------------------
def infer_program(program: Program, feed_names: Sequence[str] = (),
                  fetch_names: Sequence[str] = (),
                  scope: Optional[Scope] = None,
                  annotate: bool = True) -> ProgramAnalysis:
    """Propagate shapes/dtypes through every op of every block.

    The value frontier is exactly the executor's data-flow
    classification (core/executor.py _compile): feeds, names resident in
    ``scope``, and declared persistable/data variables; every other
    input must be produced by an earlier op. Raises
    :class:`ProgramCheckError` on an unresolvable input, a kernel that
    rejects its abstract inputs, or an inferred shape contradicting the
    declared one. Declared ``-1`` dims match the batch sentinel or any
    concrete value. With ``annotate`` (default), inferred shapes/dtypes
    are written back onto Variables whose declared shape was unknown.
    """
    result = ProgramAnalysis()
    feeds = set(feed_names)
    for block in program.blocks:
        _infer_block(block, feeds, scope, annotate, result)
    # fetches may legitimately live only in the scope (state fetches)
    for name in fetch_names:
        if name in result.types:
            continue
        if scope is not None and scope.has(name):
            result.types[name] = _sds_of_value(scope.get(name))
            continue
        v = _lookup_var(program.global_block, name)
        if v is None or not v.persistable:
            raise ProgramCheckError(
                f"fetch variable {name!r} is never produced by any op and "
                f"is not scope-resident state", var=name)
    return result


def _lookup_var(block: Block, name: str):
    b = block
    while b is not None:
        if name in b.vars:
            return b.vars[name]
        b = b.parent
    return None


def _infer_block(block: Block, feeds: set, scope: Optional[Scope],
                 annotate: bool, result: ProgramAnalysis) -> None:
    env = result.types  # shared across blocks: sub-blocks read outer names

    def resolve(name: str, *, op=None, op_index=None, slot=None):
        if name in env:
            return env[name]
        v = _lookup_var(block, name)
        if scope is not None and scope.has(name):
            sds = _sds_of_value(scope.get(name))
        elif v is not None and v.shape is not None and (
                v.persistable or v.is_data or name in feeds):
            sds = jax.ShapeDtypeStruct(v.concrete_shape(), v.dtype)
        else:
            where = (_op_loc(block, op, op_index) + f" input {slot}="
                     if op is not None else "")
            if v is None:
                kind = ("not declared in the program" +
                        ("" if scope is not None else
                         " (no scope given — pass the run-time scope to "
                         "resolve state inputs)"))
            elif v.persistable or v.is_data or name in feeds:
                kind = ("a feed/persistable variable with no declared "
                        "shape — declare the shape or provide a scope "
                        "holding its value")
            else:
                kind = ("declared but produced by no earlier op (and not "
                        "fed/persistable)")
            raise ProgramCheckError(
                f"{where}{name!r}: {kind}",
                block_idx=block.idx,
                op_index=op_index,
                op_type=op.type if op is not None else None,
                callsite=op.attrs.get("_callsite") if op is not None
                else None,
                slot=slot, var=name)
        env[name] = sds
        return sds

    def infer_op(op_type, attrs, ins, *, where="", op=None, op_index=None):
        try:
            return infer_outputs(op_type, attrs, ins)
        except ProgramCheckError:
            raise
        except Exception as exc:
            loc = (_op_loc(block, op, op_index) if op is not None
                   else f"op {op_type!r}")
            sigs = format_input_sigs({
                slot: [jax.ShapeDtypeStruct(
                    _build_shape(getattr(a, "shape", ())),
                    getattr(a, "dtype", None)) for a in arrs]
                for slot, arrs in ins.items()})
            raise ProgramCheckError(
                f"shape inference failed at {loc}{' ' + where if where else ''}\n"
                f"  inputs: {sigs}\n"
                f"  cause: {type(exc).__name__}: {exc}",
                block_idx=block.idx, op_index=op_index,
                op_type=op_type,
                callsite=(op.attrs.get("_callsite")
                          if op is not None else None)) from exc

    for op_index, op in enumerate(block.ops):
        if not has_op(op.type):
            raise ProgramCheckError(
                f"{_op_loc(block, op, op_index)}: unknown op type",
                block_idx=block.idx, op_index=op_index, op_type=op.type,
                callsite=op.attrs.get("_callsite"))
        opdef = get_op(op.type)
        if opdef.special:
            handler = SPECIAL_HANDLERS.get(op.type)
            if handler is None:
                raise ProgramCheckError(
                    f"{_op_loc(block, op, op_index)}: special op has no "
                    f"abstract handler registered in "
                    f"analysis.checker.SPECIAL_HANDLERS",
                    block_idx=block.idx, op_index=op_index,
                    op_type=op.type, callsite=op.attrs.get("_callsite"))
            outs = handler(
                op,
                lambda n: resolve(n, op=op, op_index=op_index, slot=None),
                lambda t, a, i, where="": infer_op(
                    t, a, i, where=where, op=op, op_index=op_index))
        else:
            ins = {}
            for slot, names in op.inputs.items():
                if not names:
                    continue
                ins[slot] = [resolve(n, op=op, op_index=op_index, slot=slot)
                             for n in names]
            outs = infer_op(op.type, op.attrs, ins, op=op,
                            op_index=op_index)
        if not outs:
            continue
        for slot, names in op.outputs.items():
            inferred = outs.get(slot, []) if isinstance(outs, dict) else []
            for name, sds_tree in zip(names, inferred):
                env[name] = sds_tree
                # structured values (SelectedRows sparse grads) carry a
                # dense_shape of their own — the declared [V, D] var shape
                # describes the dense view, not the pytree leaves
                if isinstance(sds_tree, jax.ShapeDtypeStruct):
                    _check_declared(block, op, op_index, slot, name,
                                    sds_tree, annotate, result)


def _shapes_compatible(declared, inferred) -> bool:
    """Declared build shape vs inferred abstract shape. A declared -1
    matches the sentinel or any concrete value (shape-polymorphic ops
    may concretise a batch dim); an inferred sentinel matches a declared
    -1 only — it IS the batch."""
    if len(declared) != len(inferred):
        return False
    for d, i in zip(declared, inferred):
        if d == -1 or d == BATCH_DIM_SENTINEL:
            continue
        if int(d) != int(i):
            return False
    return True


def _check_declared(block: Block, op: Operator, op_index: int, slot: str,
                    name: str, sds: jax.ShapeDtypeStruct, annotate: bool,
                    result: ProgramAnalysis) -> None:
    v = _lookup_var(block, name)
    if v is None:
        return
    if v.shape is None:
        if annotate:
            v.shape = _build_shape(sds.shape)
            v.dtype = sds.dtype
        return
    if not _shapes_compatible(v.shape, sds.shape):
        raise ProgramCheckError(
            f"shape mismatch at {_op_loc(block, op, op_index)}, output "
            f"slot {slot!r} -> variable {name!r}: kernel infers shape "
            f"{_fmt_shape(sds.shape)} but the variable declares "
            f"{tuple(v.shape)}",
            block_idx=block.idx, op_index=op_index, op_type=op.type,
            callsite=op.attrs.get("_callsite"), slot=slot, var=name)
    if np.dtype(v.dtype) != np.dtype(sds.dtype):
        # dtype drift is reported, not fatal: the AMP policy legally
        # changes kernel compute dtypes after a program was built
        result.issues.append(LintIssue(
            rule="dtype-drift", severity=WARNING,
            message=f"output slot {slot!r} -> variable {name!r}: kernel "
                    f"infers dtype {np.dtype(sds.dtype).name} but the "
                    f"variable declares {np.dtype(v.dtype).name}",
            block_idx=block.idx, op_index=op_index, op_type=op.type,
            callsite=op.attrs.get("_callsite"), slot=slot, var=name))


def check_program(program: Program, feed_names: Sequence[str] = (),
                  fetch_names: Sequence[str] = (),
                  scope: Optional[Scope] = None, annotate: bool = True,
                  rules: Optional[Sequence] = None) -> ProgramAnalysis:
    """The full static checker: structural verification (every error-
    severity lint rule) followed by whole-program shape/dtype inference.

    Raises :class:`~paddle_tpu.analysis.verifier.ProgramVerifyError` on
    structural violations and :class:`ProgramCheckError` on shape/dtype
    ones; returns the :class:`ProgramAnalysis` (inferred types + warning
    issues, structural warnings included) when the program is clean.
    """
    from .verifier import verify_program

    warnings = verify_program(program, feed_names, fetch_names,
                              scope=scope, rules=rules)
    analysis = infer_program(program, feed_names, fetch_names, scope=scope,
                             annotate=annotate)
    analysis.issues.extend(warnings)
    return analysis

"""Lint plane: LintIssue, LintRule base class, and the rule registry.

Mirrors the transpiler's pass registry (transpiler/framework.py): rules
are small named checks registered under a string key, instantiated per
run, and composable into rule sets. The program verifier
(analysis/verifier.py) and the whole-program shape checker
(analysis/checker.py) are both surfaced as rules here, so
``tools/proglint.py`` and ``PassManager(verify_each=True)`` run one
shared battery.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Iterable, List, Optional, Sequence

from ..core.program import Program
from ..core.scope import Scope

ERROR = "error"
WARNING = "warning"


@dataclasses.dataclass
class LintIssue:
    """One finding. ``severity`` is ``"error"`` (the program would fail
    or silently miscompute at run time) or ``"warning"`` (suspicious but
    executable)."""

    rule: str
    severity: str
    message: str
    block_idx: int = 0
    op_index: Optional[int] = None
    op_type: Optional[str] = None
    callsite: Optional[str] = None
    slot: Optional[str] = None
    var: Optional[str] = None

    def format(self) -> str:
        loc = f"block {self.block_idx}"
        if self.op_index is not None:
            loc += f" op #{self.op_index}"
        if self.op_type:
            loc += f" {self.op_type!r}"
        site = f" (created at {self.callsite})" if self.callsite else ""
        return (f"[{self.severity}] {self.rule}: {loc}{site}: "
                f"{self.message}")

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


class LintContext:
    """What a rule may consult: the feed/fetch contract and (optionally)
    the scope holding run-time state — names resident in the scope count
    as available inputs, exactly as the executor classifies them."""

    def __init__(self, feed_names: Sequence[str] = (),
                 fetch_names: Sequence[str] = (),
                 scope: Optional[Scope] = None):
        self.feed_names = list(feed_names)
        self.fetch_names = list(fetch_names)
        self.scope = scope


class LintRule:
    """Base class: subclass, set ``name``, implement ``check``.

    ``check(program, ctx)`` returns/yields :class:`LintIssue`s and must
    not mutate the program.
    """

    name: str = ""

    def check(self, program: Program,
              ctx: LintContext) -> Iterable[LintIssue]:
        raise NotImplementedError

    def __repr__(self):
        return f"{type(self).__name__}(name={self.name!r})"


# --------------------------------------------------------------------------
# Registry: name -> LintRule factory (zero-arg callable)
# --------------------------------------------------------------------------
_RULE_REGISTRY: Dict[str, Callable[[], LintRule]] = {}


def register_rule(factory: Callable[[], LintRule] = None, *,
                  name: Optional[str] = None):
    """Register a LintRule class (or zero-arg factory) under its
    ``name``. Usable as a decorator on LintRule subclasses."""

    def _do(f):
        key = name or getattr(f, "name", "") or getattr(f, "__name__", "")
        if not key:
            raise ValueError("lint rule factory needs a name")
        if key in _RULE_REGISTRY:
            raise ValueError(f"lint rule {key!r} already registered")
        _RULE_REGISTRY[key] = f
        return f

    if factory is None:
        return _do
    return _do(factory)


def get_rule(name: str) -> LintRule:
    if name not in _RULE_REGISTRY:
        raise KeyError(f"lint rule {name!r} is not registered "
                       f"(known: {sorted(_RULE_REGISTRY)})")
    return _RULE_REGISTRY[name]()


def registered_rules() -> List[str]:
    return sorted(_RULE_REGISTRY)


def run_lint(program: Program, feed_names: Sequence[str] = (),
             fetch_names: Sequence[str] = (),
             scope: Optional[Scope] = None,
             rules: Optional[Sequence] = None, *,
             warnings_as_errors: bool = False,
             severity: Optional[str] = None) -> List[LintIssue]:
    """Run a rule battery (default: every registered rule) and return
    every issue found, errors first.

    Programmatic callers get the same contract as the ``tools/proglint``
    CLI flags: ``warnings_as_errors`` promotes every warning finding to
    error severity (the returned issues carry ``severity="error"``, so
    downstream gates that branch on severity fail exactly as the CLI
    would exit nonzero); ``severity`` filters the returned issues to one
    level (``"error"`` or ``"warning"``, applied BEFORE promotion so
    ``severity="warning"`` still selects the promoted findings).
    """
    if severity is not None and severity not in (ERROR, WARNING):
        raise ValueError(
            f"severity must be {ERROR!r} or {WARNING!r}, got {severity!r}")
    ctx = LintContext(feed_names, fetch_names, scope=scope)
    battery = [get_rule(r) if isinstance(r, str) else r
               for r in (rules if rules is not None else registered_rules())]
    issues: List[LintIssue] = []
    for rule in battery:
        issues.extend(rule.check(program, ctx))
    if severity is not None:
        issues = [i for i in issues if i.severity == severity]
    if warnings_as_errors:
        issues = [dataclasses.replace(i, severity=ERROR)
                  if i.severity == WARNING else i for i in issues]
    issues.sort(key=lambda i: (i.severity != ERROR, i.block_idx,
                               -1 if i.op_index is None else i.op_index))
    return issues


def format_issues(issues: Sequence[LintIssue]) -> str:
    if not issues:
        return "(no issues)"
    return "\n".join(i.format() for i in issues)

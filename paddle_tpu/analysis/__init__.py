"""paddle_tpu.analysis — the static-analysis plane.

The correctness backstop under every transpiler rewrite and sharding
pass: a whole-program shape/dtype checker driven by the kernels
themselves (``registry.infer_outputs`` / ``jax.eval_shape``), a
structural program verifier, and an extensible lint-rule registry
mirroring the transpiler's pass registry. Shape/dtype bugs, dangling
variables, and broken rewrites fail at BUILD time with the op index,
type, user callsite, and offending slot named — not as opaque JAX trace
errors deep inside ``jit``.

Typical use::

    from paddle_tpu import analysis

    # raise on any structural or shape/dtype violation
    analysis.check_program(program, feed_names, fetch_names, scope=scope)

    # collect findings instead (tools/proglint.py does this)
    issues = analysis.run_lint(program, feed_names, fetch_names)

    # blame the exact pass that broke a program
    pm = transpiler.inference_pipeline(verify_each=True)

``PassManager(verify_each=True)`` re-verifies after every pass (the
pass sandwich); the ``--verify_program`` flag turns it on across the
inference/training/deployment pipelines, the trainer, and the serving
warmup path. ``tools/proglint.py`` runs the battery over built programs
and saved inference models from the command line.
"""
from __future__ import annotations

from .checker import (ProgramAnalysis, ProgramCheckError, SPECIAL_HANDLERS,
                      check_program, infer_program)
from .conformance import audit_op, audit_op_registry
from .costmodel import (OpCost, cost_exempt, has_cost, is_cost_exempt,
                        op_cost, register_cost)
from .lint import (ERROR, WARNING, LintContext, LintIssue, LintRule,
                   format_issues, get_rule, register_rule, registered_rules,
                   run_lint)
from .memory import (LiveTensor, MemoryAnalysis, MemoryBudgetError,
                     RematAdvice, advise_recompute, analyze_memory,
                     check_memory_budget)
from .sharding import (CollectiveRow, ShardingCost, V5E_ICI_BW,
                       estimate_collectives)
from .verifier import (ProgramVerifyError, check_async_overlap,
                       verify_program, written_state_names)

__all__ = [
    "ProgramAnalysis", "ProgramCheckError", "ProgramVerifyError",
    "LintIssue", "LintRule", "LintContext", "ERROR", "WARNING",
    "check_program", "infer_program", "verify_program", "run_lint",
    "register_rule", "get_rule", "registered_rules", "format_issues",
    "audit_op", "audit_op_registry", "written_state_names",
    "check_async_overlap", "SPECIAL_HANDLERS",
    # memory & roofline plane
    "MemoryAnalysis", "MemoryBudgetError", "LiveTensor", "RematAdvice",
    "analyze_memory", "check_memory_budget", "advise_recompute",
    "OpCost", "register_cost", "cost_exempt", "has_cost",
    "is_cost_exempt", "op_cost",
    # sharding plane
    "ShardingCost", "CollectiveRow", "estimate_collectives",
    "V5E_ICI_BW",
]

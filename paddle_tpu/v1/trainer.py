"""The v1 training entry point: what ``paddle_trainer --config=...`` did.

Reference: paddle/trainer/TrainerMain.cpp + Trainer.cpp drive passes over
the config's data provider, batching rows and calling the gradient
machine. Here :func:`train_from_config` parses the config, wires the
provider into a batched reader, builds the optimizer from settings(), and
runs the executor train loop — the whole v1 workflow in one call.
"""
from __future__ import annotations

import os
from typing import Callable, Optional

import numpy as np

from ..core.executor import Executor, TPUPlace
from ..core.scope import Scope
from ..data_feeder import DataFeeder
from ..reader.minibatch import batch as _batch
from . import data_provider as _dp
from .config_parser import ParsedConfig, parse_config


class V1DataFeeder(DataFeeder):
    """DataFeeder that additionally understands rows from PyDataProvider2
    providers: dict rows (keyed by data-layer name) are reordered to the
    feed order, and sparse *_sequence columns (per-timestep id lists) are
    rectangularized to [T, Kmax] with -1 padding before the base feeder
    pads the time axis."""

    def feed(self, data):
        names = [v.name for v in self.feed_vars]
        rows = [[row[n] for n in names] if isinstance(row, dict) else row
                for row in data]
        for i, var in enumerate(self.feed_vars):
            if not getattr(var, "sparse_seq", False):
                continue
            col = [row[i] for row in rows]
            kmax = max((len(ids) for seq in col for ids in seq),
                       default=1) or 1
            fixed = []
            for seq in col:
                arr = np.full((len(seq), kmax), -1, dtype=np.int64)
                for t, ids in enumerate(seq):
                    arr[t, :len(ids)] = ids
                fixed.append(arr)
            rows = [list(r) for r in rows]
            for r, arr in zip(rows, fixed):
                r[i] = arr
        return super().feed(rows)


def make_reader(parsed: ParsedConfig, split: str = "train"):
    """Batched reader over the config's define_py_data_sources2 sources:
    iterates the ``<split>_list`` file's data-file paths through the
    provider generator. Honors CACHE_PASS_IN_MEM."""
    ds = parsed.data_sources or {}
    provider = ds.get("provider")
    settings = ds.get("provider_settings")
    list_file = ds.get(f"{split}_list")
    if provider is None or list_file is None:
        raise ValueError(
            f"config has no usable {split} data source (module "
            f"{ds.get('module')!r} must expose a @provider {ds.get('obj')!r})")
    def resolve(path):
        """Relative data paths resolve against the CWD first (the
        reference trainer's contract — configs say './data/...' and
        paddle_trainer runs from the demo dir), then the config dir."""
        if os.path.isabs(path) or os.path.exists(path):
            return path
        alt = os.path.join(parsed.config_dir, path)
        return alt if os.path.exists(alt) else path

    list_file = resolve(list_file)
    cache = [] if provider.cache == _dp.CacheType.CACHE_PASS_IN_MEM else None
    batch_size = int(parsed.settings.get("batch_size", 100))

    def row_reader():
        if cache:
            yield from cache
            return
        with open(list_file) as fh:
            files = [ln.strip() for ln in fh if ln.strip()]
        for fname in files:
            for row in provider(settings, resolve(fname)):
                if cache is not None:
                    cache.append(row)
                yield row

    return _batch(row_reader, batch_size)


def train_from_config(config_file, config_arg_str: str = "",
                      num_passes: int = 1,
                      event_handler: Optional[Callable] = None,
                      scope: Optional[Scope] = None):
    """Parse + train: the ``paddle_trainer`` one-shot. Returns
    (parsed_config, scope, per-pass mean costs)."""
    parsed = parse_config(config_file, config_arg_str)
    optimizer = parsed.build_optimizer()
    from .. import layers as L
    from ..core.program import program_guard

    # v1 cost layers are per-row ([b, 1], e.g. crf nll); the trainer
    # optimizes their batch mean (reference Trainer.cpp cost averaging)
    with program_guard(parsed.main_program, parsed.startup_program):
        cost = L.mean(parsed.cost)
        optimizer.minimize(cost, startup_program=parsed.startup_program)
    scope = scope or Scope()
    exe = Executor(TPUPlace())
    exe.run(parsed.startup_program, scope=scope)
    feeder = V1DataFeeder(parsed.input_vars)
    reader = make_reader(parsed)  # one reader: CACHE_PASS_IN_MEM replays
    pass_costs = []
    for pass_id in range(num_passes):
        costs = []
        for batch_id, rows in enumerate(reader()):
            out, = exe.run(parsed.main_program, feed=feeder.feed(rows),
                           fetch_list=[cost], scope=scope)
            costs.append(float(np.mean(np.asarray(out))))
            if event_handler is not None:
                event_handler({"pass": pass_id, "batch": batch_id,
                               "cost": costs[-1]})
        pass_costs.append(float(np.mean(costs)) if costs else 0.0)
    return parsed, scope, pass_costs


def time_from_config(config_file, config_arg_str: str = "",
                     n_batches: int = 5, warmup: int = 2):
    """The ``--job=time`` job (reference TrainerMain.cpp:58
    trainer.time() / Trainer::time): time forward+backward+update over a
    few batches and report per-op device time. On TPU the step is one
    compiled XLA program, so the per-layer table the reference prints
    becomes (a) the wall per step and (b) the profiler's per-op stats
    when the xprof converter is available. Returns the timing dict."""
    import time as _time

    from .. import profiler
    from ..core.program import program_guard

    parsed = parse_config(config_file, config_arg_str)
    optimizer = parsed.build_optimizer()
    from .. import layers as L

    with program_guard(parsed.main_program, parsed.startup_program):
        cost = L.mean(parsed.cost)
        optimizer.minimize(cost, startup_program=parsed.startup_program)
    scope = Scope()
    exe = Executor(TPUPlace())
    exe.run(parsed.startup_program, scope=scope)
    feeder = V1DataFeeder(parsed.input_vars)
    reader = make_reader(parsed)
    batches = []
    for rows in reader():
        batches.append(feeder.feed(rows))
        if len(batches) >= max(n_batches, warmup + 1):
            break
    if not batches:
        raise RuntimeError("--job=time: the train reader yielded no "
                           "batches")
    for i in range(warmup):
        exe.run(parsed.main_program, feed=batches[i % len(batches)],
                fetch_list=[cost], scope=scope)
    stats = profiler.StatSet()
    t0 = _time.perf_counter()
    for i in range(n_batches):
        with profiler.timer("train_step", stats, sync=True,
                            block_on=None):
            out, = exe.run(parsed.main_program,
                           feed=batches[i % len(batches)],
                           fetch_list=[cost], scope=scope,
                           return_numpy=False)
    np.asarray(out)
    total = _time.perf_counter() - t0
    result = {"batches": n_batches,
              "ms_per_batch": round(total / n_batches * 1e3, 3),
              "stats": stats.format()}
    print(f"--job=time: {n_batches} batches, "
          f"{result['ms_per_batch']} ms/batch")
    print(stats.format())
    return result


def test_from_config(config_file, config_arg_str: str = ""):
    """The ``--job=test`` job: one forward pass over the test_list,
    reporting the mean cost (reference Trainer::test)."""
    parsed = parse_config(config_file, config_arg_str)
    scope = Scope()
    exe = Executor(TPUPlace())
    exe.run(parsed.startup_program, scope=scope)
    feeder = V1DataFeeder(parsed.input_vars)
    split = "test"
    if not (parsed.data_sources or {}).get("test_list"):
        print("--job=test: config has no test_list; evaluating the "
              "train source")
        split = "train"
    reader = make_reader(parsed, split=split)
    costs = []
    for rows in reader():
        out, = exe.run(parsed.main_program, feed=feeder.feed(rows),
                       fetch_list=[parsed.cost], scope=scope)
        costs.append(float(np.mean(np.asarray(out))))
    mean = float(np.mean(costs)) if costs else 0.0
    print(f"--job=test: {len(costs)} batches, mean cost {mean:.6f}")
    return mean


def checkgrad_from_config(config_file, config_arg_str: str = ""):
    """The ``--job=checkgrad`` job (reference Trainer::checkGradient):
    finite-difference check of the config's cost gradients."""
    from .. import checkgrad as _cg
    from .. import layers as L
    from ..core.program import program_guard

    parsed = parse_config(config_file, config_arg_str)
    with program_guard(parsed.main_program, parsed.startup_program):
        cost = L.mean(parsed.cost)
    scope = Scope()
    exe = Executor(TPUPlace())
    exe.run(parsed.startup_program, scope=scope)
    feeder = V1DataFeeder(parsed.input_vars)
    rows = next(iter(make_reader(parsed)()))
    report = _cg.check_gradients(parsed.main_program, feeder.feed(rows),
                                 cost, scope=scope, executor=exe,
                                 startup_program=parsed.startup_program)
    for name, err in report:
        print(f"checkgrad {name}: max rel err {err:.2e}")
    return report


def main(argv=None):
    """``python -m paddle_tpu.v1.trainer --config=... --job=...`` — the
    paddle_trainer command-line entry (TrainerMain.cpp:32)."""
    import argparse

    p = argparse.ArgumentParser(prog="paddle_trainer")
    p.add_argument("--config", required=True)
    p.add_argument("--config_args", default="")
    p.add_argument("--job", default="train",
                   choices=["train", "test", "checkgrad", "time"])
    p.add_argument("--num_passes", type=int, default=1)
    args = p.parse_args(argv)
    if args.job == "train":
        _, _, costs = train_from_config(args.config, args.config_args,
                                        num_passes=args.num_passes)
        for i, c in enumerate(costs):
            print(f"pass {i}: mean cost {c:.6f}")
        return 0
    if args.job == "test":
        test_from_config(args.config, args.config_args)
        return 0
    if args.job == "checkgrad":
        checkgrad_from_config(args.config, args.config_args)
        return 0
    time_from_config(args.config, args.config_args)
    return 0


if __name__ == "__main__":
    import sys as _sys

    _sys.exit(main())

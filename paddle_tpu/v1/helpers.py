"""trainer_config_helpers compatibility namespace — the v1 config DSL.

This is the surface a reference v1 config file sees after
``from paddle.trainer_config_helpers import *``
(/root/reference/python/paddle/trainer_config_helpers/layers.py et al.).
Each builder delegates to the v2 facade / fluid layers and records
config-level state (settings, data sources, inputs/outputs, evaluators)
into the active :class:`ParseContext` — the role the reference's global
``g_config`` plays in config_parser.py.

Input typing: the v1 DSL's ``data_layer(name, size)`` carries no dtype or
sparsity — in the reference those come from the DATA PROVIDER's
input_types at runtime. ``define_py_data_sources2`` therefore resolves the
provider eagerly (imports the module, runs the init_hook) so data_layer
can claim its InputType: by name when the provider declares a dict, by
best dimension match when it declares a positional list (the reference
matches positionally against the ``inputs()`` order, which is not yet
known at data_layer time; dimension matching reproduces it for real
configs, and ambiguity raises with a pointer to dict declarations).
"""
from __future__ import annotations

import importlib
import math
import os
import sys
from typing import Optional

from .. import layers as L
from .. import optimizer as _opt
from ..initializer import (ConstantInitializer, NormalInitializer,
                           UniformInitializer)
from ..param_attr import ParamAttr as _FluidParamAttr
from ..regularizer import L1DecayRegularizer, L2DecayRegularizer
from ..v2 import layer as v2l
from ..v2.data_type import InputType, dense_vector
from . import data_provider as _dp

# ---------------------------------------------------------------------------
# parse context
# ---------------------------------------------------------------------------

_CTX = None  # the active ParseContext (set by config_parser.parse_config)


class ParseContext:
    def __init__(self, config_args=None, config_dir="."):
        self.config_args = dict(config_args or {})
        self.config_dir = config_dir
        self.settings = {
            "batch_size": 100,
            "learning_rate": 0.01,
            "learning_method": None,
            "regularization": None,
            "gradient_clipping_threshold": None,
            "model_average": None,
        }
        self.data_sources = None       # define_py_data_sources2 record
        self.provider_types = None     # dict name->InputType | list
        self._claimed = set()          # claimed positional slots
        self.data_layers = []          # creation order
        self.inputs_order = None       # inputs() override
        self.outputs = None
        self.evaluators = []
        self.named_layers = {}         # v1 name= kwarg -> built var
        self.default_momentum = None   # default_momentum()
        self.default_decay_rate = None  # default_decay_rate()


def _ctx() -> ParseContext:
    if _CTX is None:
        raise RuntimeError(
            "the v1 DSL must run under parse_config() "
            "(paddle_tpu.v1.parse_config)")
    return _CTX


# ---------------------------------------------------------------------------
# config-level declarations
# ---------------------------------------------------------------------------

def get_config_arg(name, type_=str, default=None):
    """Read a --config_args key (reference config_parser.py
    get_config_arg)."""
    val = _ctx().config_args.get(name)
    if val is None:
        return default
    if type_ is bool:
        return str(val).lower() not in ("0", "false", "")
    return type_(val)


def define_py_data_sources2(train_list, test_list, module, obj, args=None):
    """Record the data sources and eagerly resolve the provider's
    input_types (reference trainer/config_parser data_sources handling) so
    data_layer() can type its feeds."""
    ctx = _ctx()
    ctx.data_sources = {"train_list": train_list, "test_list": test_list,
                        "module": module, "obj": obj,
                        "args": dict(args or {})}
    sys_path_added = ctx.config_dir not in sys.path
    if sys_path_added:
        sys.path.insert(0, ctx.config_dir)
    try:
        mod = importlib.import_module(module)
    except Exception:  # noqa: BLE001 - unimportable provider (missing, or
        # py2-only like the reference sequence_tagging dataprovider):
        # data_layer falls back to dense typing; training needs a usable
        # provider but parsing should not
        return
    finally:
        if sys_path_added:
            sys.path.remove(ctx.config_dir)
    dp = getattr(mod, obj, None)
    if isinstance(dp, _dp.DataProvider):
        # the TRAIN source's files only — the reference hands each data
        # source its own provider instance and file_list
        # (PyDataProvider2.py:434); hooks deriving state (vocabs, class
        # counts) must not also see the test files
        file_list = []
        lst = train_list or test_list
        if lst:
            for base in (os.getcwd(), ctx.config_dir):
                path = lst if os.path.isabs(lst) else os.path.join(base,
                                                                   lst)
                if os.path.exists(path):
                    with open(path) as lf:
                        file_list.extend(
                            ln.strip() for ln in lf if ln.strip())
                    break
        try:
            settings = dp.create(file_list=file_list,
                                 **ctx.data_sources["args"])
        except (NameError, AttributeError, SyntaxError, ImportError):
            # py2-only init hooks (xrange, dict.iteritems, ...): degrade
            # to dense typing like an unimportable module — but say so,
            # because the feeds lose their provider types
            import traceback
            import warnings

            warnings.warn(
                f"provider {module}.{obj} init hook failed "
                f"(py2-only?); data layers degrade to dense typing:\n"
                f"{traceback.format_exc()}", stacklevel=2)
            return
        ctx.provider_types = settings.input_types
        ctx.data_sources["provider"] = dp
        ctx.data_sources["provider_settings"] = settings


def settings(batch_size=None, learning_rate=None, learning_method=None,
             regularization=None, gradient_clipping_threshold=None,
             model_average=None, **kw):
    """The v1 settings() call (reference trainer_config_helpers/
    optimizers.py settings): records the optimization recipe; the trainer
    materializes it via build_optimizer()."""
    ctx = _ctx()
    for k, v in [("batch_size", batch_size),
                 ("learning_rate", learning_rate),
                 ("learning_method", learning_method),
                 ("regularization", regularization),
                 ("gradient_clipping_threshold",
                  gradient_clipping_threshold),
                 ("model_average", model_average)]:
        if v is not None:
            ctx.settings[k] = v
    ctx.settings.update(kw)  # decay_a/b etc. kept for inspection


def inputs(*layers_):
    _ctx().inputs_order = [getattr(v, "name", v) for v in layers_]


def outputs(*layers_):
    flat = []
    for item in layers_:
        flat.extend(item if isinstance(item, (list, tuple)) else [item])
    _ctx().outputs = flat


def Inputs(*names):
    """Name-string form (reference config_parser Inputs): the feed order
    by data-layer name."""
    _ctx().inputs_order = list(names)


def Outputs(*names):
    """Name-string form (reference config_parser Outputs): entries are
    v1 layer names resolved against the name registry at parse end."""
    _ctx().outputs = list(names)


def default_momentum(momentum):
    """Config-wide momentum default consumed by Settings(
    learning_method='momentum') (reference config_parser
    default_momentum)."""
    _ctx().default_momentum = float(momentum)


def default_decay_rate(rate):
    """Config-wide L2 decay default (reference default_decay_rate)."""
    _ctx().default_decay_rate = float(rate)


def default_initial_std(std):
    """Accepted no-op: per-layer attrs carry their own initializers."""


def default_initial_mean(mean):
    """Accepted no-op (see default_initial_std)."""


def Settings(algorithm="sgd", batch_size=None, learning_rate=None,
             learning_method=None, learning_rate_decay_a=None,
             learning_rate_decay_b=None, learning_rate_schedule=None,
             **kw):
    """The capitalized low-level form (reference config_parser Settings):
    ``learning_method`` arrives as a STRING and is recorded AS-IS —
    resolution to an optimizer object happens lazily in
    build_optimizer, because the reference reads default_momentum()/
    default_decay_rate() at parameter-build time, so configs may call
    them in any order relative to Settings()."""
    settings(batch_size=batch_size, learning_rate=learning_rate,
             learning_method=learning_method,
             learning_rate_decay_a=learning_rate_decay_a,
             learning_rate_decay_b=learning_rate_decay_b,
             learning_rate_schedule=learning_rate_schedule, **kw)


def resolve_learning_method(method, default_momentum=None):
    """STRING learning_method -> optimizer object (reference
    config_parser Settings algorithm table). Momentum defaults to the
    reference's 0.0 unless default_momentum() was called; unknown
    methods fail loudly."""
    if not isinstance(method, str):
        return method
    mom = default_momentum if default_momentum is not None else 0.0
    table = {
        "momentum": lambda: MomentumOptimizer(momentum=mom),
        # the sparse variant differs only in pserver-side update layout;
        # sparse gradients here are SelectedRows either way
        "sparse_momentum": lambda: MomentumOptimizer(momentum=mom),
        "sgd": lambda: MomentumOptimizer(momentum=mom),
        "adam": AdamOptimizer,
        "adamax": AdamaxOptimizer,
        "adagrad": AdaGradOptimizer,
        "decayed_adagrad": DecayedAdaGradOptimizer,
        "adadelta": AdaDeltaOptimizer,
        "rmsprop": RMSPropOptimizer,
    }
    if method not in table:
        raise ValueError(
            f"Settings(learning_method={method!r}) is not a supported "
            f"method; known: {sorted(table)}")
    return table[method]()


# ---------------------------------------------------------------------------
# settings objects: optimizers / regularization / model average
# ---------------------------------------------------------------------------

class _V1Optimizer:
    factory = None
    kwargs = {}

    def build(self, learning_rate, regularization=None):
        return type(self).factory(learning_rate=learning_rate,
                                  regularization=regularization,
                                  **self.kwargs)


Optimizer = _V1Optimizer            # reference optimizers.py base names
BaseSGDOptimizer = _V1Optimizer


class BaseRegularization:
    """Base marker (reference optimizers.py BaseRegularization)."""


class AdamOptimizer(_V1Optimizer):
    factory = _opt.AdamOptimizer

    def __init__(self, beta1=0.9, beta2=0.999, epsilon=1e-8):
        self.kwargs = {"beta1": beta1, "beta2": beta2, "epsilon": epsilon}


class AdamaxOptimizer(_V1Optimizer):
    factory = _opt.AdamaxOptimizer

    def __init__(self, beta1=0.9, beta2=0.999):
        self.kwargs = {"beta1": beta1, "beta2": beta2}


class MomentumOptimizer(_V1Optimizer):
    factory = _opt.MomentumOptimizer

    def __init__(self, momentum=0.9, sparse=False):
        self.kwargs = {"momentum": momentum}


class AdaGradOptimizer(_V1Optimizer):
    factory = _opt.AdagradOptimizer

    def __init__(self):
        self.kwargs = {}


class DecayedAdaGradOptimizer(_V1Optimizer):
    factory = _opt.DecayedAdagradOptimizer

    def __init__(self, rho=0.95, epsilon=1e-6):
        self.kwargs = {"decay": rho, "epsilon": epsilon}


class AdaDeltaOptimizer(_V1Optimizer):
    factory = _opt.AdadeltaOptimizer

    def __init__(self, rho=0.95, epsilon=1e-6):
        self.kwargs = {"rho": rho, "epsilon": epsilon}


class RMSPropOptimizer(_V1Optimizer):
    factory = _opt.RMSPropOptimizer

    def __init__(self, rho=0.95, epsilon=1e-6):
        self.kwargs = {"decay": rho, "epsilon": epsilon}


def L2Regularization(rate):
    return L2DecayRegularizer(regularization_coeff=rate)


def L1Regularization(rate):
    return L1DecayRegularizer(regularization_coeff=rate)


class ModelAverage:
    """settings(model_average=ModelAverage(w)) marker (the trainer may wire
    it to optimizer.ModelAverage)."""

    def __init__(self, average_window, max_average_window=None):
        self.average_window = average_window
        self.max_average_window = max_average_window


# ---------------------------------------------------------------------------
# activations / poolings / attrs
# ---------------------------------------------------------------------------

from ..v2 import activation as _act  # noqa: E402
from ..v2 import pooling as _pool  # noqa: E402

BaseActivation = _act.BaseActivation
LinearActivation = _act.Linear
IdentityActivation = _act.Linear
SqrtActivation = _act.Sqrt
ReciprocalActivation = _act.Reciprocal
SoftSignActivation = _act.SoftSign
ReluActivation = _act.Relu
BReluActivation = _act.BRelu
SoftReluActivation = _act.SoftRelu
TanhActivation = _act.Tanh
STanhActivation = _act.STanh
SigmoidActivation = _act.Sigmoid
SoftmaxActivation = _act.Softmax
ExpActivation = _act.Exp
LogActivation = _act.Log
AbsActivation = _act.Abs
SquareActivation = _act.Square
SequenceSoftmaxActivation = _act.SequenceSoftmax

BasePoolingType = _pool.BasePooling
MaxPooling = _pool.Max
AvgPooling = _pool.Avg
SumPooling = _pool.Sum
SquareRootNPooling = _pool.SquareRootN
# cudnn-flavored names are device aliases of the same math here
CudnnMaxPooling = _pool.Max
CudnnAvgPooling = _pool.Avg
CudnnAvgInclPadPooling = _pool.Avg
MaxWithMaskPooling = _pool.Max  # the mask is implicit in XLA's reduce


class ParamAttr:
    """v1 ParameterAttribute (reference trainer_config_helpers/attrs.py):
    translated onto the fluid ParamAttr at use time."""

    def __init__(self, name=None, is_static=False, initial_std=None,
                 initial_mean=None, initial_max=None, initial_min=None,
                 l1_rate=None, l2_rate=None, learning_rate=None,
                 momentum=None, gradient_clipping_threshold=None,
                 sparse_update=False, initializer=None,
                 update_hooks=None):
        self.update_hooks = update_hooks
        self.name = name
        self.is_static = is_static
        self.initial_std = initial_std
        self.initial_mean = initial_mean
        self.initial_max = initial_max
        self.initial_min = initial_min
        self.l1_rate = l1_rate
        self.l2_rate = l2_rate
        self.learning_rate = learning_rate
        self.sparse_update = sparse_update
        self.initializer = initializer
        self.gradient_clipping_threshold = gradient_clipping_threshold

    def to_fluid(self):
        init = self.initializer
        if init is None and self.initial_std is not None:
            if self.initial_std == 0 and not self.initial_mean:
                init = ConstantInitializer(0.0)
            else:
                init = NormalInitializer(loc=self.initial_mean or 0.0,
                                         scale=self.initial_std)
        elif init is None and self.initial_max is not None:
            init = UniformInitializer(low=self.initial_min or 0.0,
                                      high=self.initial_max)
        reg = None
        if self.l2_rate:
            reg = L2DecayRegularizer(regularization_coeff=self.l2_rate)
        elif self.l1_rate:
            reg = L1DecayRegularizer(regularization_coeff=self.l1_rate)
        from ..clip import GradientClipByNorm

        clip = (GradientClipByNorm(self.gradient_clipping_threshold)
                if self.gradient_clipping_threshold else None)
        hooks = self.update_hooks
        if hooks is not None and not isinstance(hooks, (list, tuple)):
            hooks = [hooks]
        hooks = [h.to_fluid_hook() if isinstance(h, HookAttribute) else h
                 for h in (hooks or [])]
        return _FluidParamAttr(
            name=self.name, initializer=init,
            learning_rate=self.learning_rate
            if self.learning_rate is not None else 1.0,
            regularizer=reg, trainable=not self.is_static,
            gradient_clip=clip, update_hooks=hooks or None)


ParameterAttribute = ParamAttr


class HookAttribute:
    """Parameter update hook (reference attrs.py HookAttribute):
    'pruning' with a sparsity_ratio — carried onto the fluid ParamAttr's
    update_hooks plane (param_attr.py)."""

    def __init__(self, type="pruning", sparsity_ratio=0.6):
        if type != "pruning":
            raise ValueError(f"unsupported hook type {type!r} "
                             "(only 'pruning' is registered)")
        self.type = type
        self.sparsity_ratio = float(sparsity_ratio)

    def to_fluid_hook(self):
        from ..param_attr import Hook

        return Hook("pruning", sparsity_ratio=self.sparsity_ratio)


HookAttr = HookAttribute


def _pa(attr):
    """None | bool | v1 ParamAttr | fluid ParamAttr -> fluid attr.
    True means "default attribute" in the v1 DSL."""
    if isinstance(attr, ParamAttr):
        return attr.to_fluid()
    if attr is True:
        return None
    return attr


class ExtraLayerAttribute:
    """v1 ExtraLayerAttribute (reference attrs.py): ``drop_rate`` is
    honored (the wrapper applies dropout to the layer output, the role
    LayerConfig.drop_rate plays in the reference); ``device`` and
    ``error_clipping_threshold`` are accepted no-ops (there is no
    per-layer device placement under one compiled XLA program)."""

    def __init__(self, error_clipping_threshold=None, drop_rate=None,
                 device=None):
        self.drop_rate = drop_rate


ExtraAttr = ExtraLayerAttribute


def _maybe_drop(var, kw):
    """Apply layer_attr=ExtraAttr(drop_rate=...) to a layer output."""
    rate = getattr(kw.get("layer_attr"), "drop_rate", None)
    if rate:
        var = v2l.dropout_keep_len(var, rate)
    return var


def default_device(device=0):
    """Accepted no-op: per-layer device placement does not exist under a
    single compiled XLA program (sharding is the plan's job)."""


# ---------------------------------------------------------------------------
# input-type resolution for data_layer
# ---------------------------------------------------------------------------

def _resolve_input_type(name, size):
    """Claim this data layer's InputType from the provider declaration."""
    ctx = _ctx()
    types = ctx.provider_types
    if isinstance(types, dict):
        t = types.get(name)
        if t is not None:
            return t
    elif isinstance(types, (list, tuple)):
        # positional list: the reference matches slots to the inputs()
        # order, unknown at this point — recover the pairing by dimension.
        exact = [i for i, t in enumerate(types)
                 if i not in ctx._claimed and t.dim == size]
        loose = [i for i, t in enumerate(types)
                 if i not in ctx._claimed and t.dim <= size]
        pick = exact or loose
        if len(pick) >= 1:
            # several equal dims: claim in declaration order (matches the
            # reference when creation order follows inputs() order for the
            # tied slots)
            ctx._claimed.add(pick[0])
            return types[pick[0]]
        raise ValueError(
            f"data_layer({name!r}, size={size}): no unclaimed provider "
            f"input_type slot fits; declare input_types as a dict keyed "
            f"by layer name to disambiguate")
    return dense_vector(size)


# ---------------------------------------------------------------------------
# layers
# ---------------------------------------------------------------------------

def data_layer(name, size, height=None, width=None, **kw):
    t = _resolve_input_type(name, size)
    if t.sparse and t.seq_type:
        # per-timestep sparse id lists: [b, T, K] ids, K-padded with -1;
        # fc masks the pads (see _sparse_seq_fc_branch)
        var = L.data(name, shape=[-1], dtype="int64", lod_level=1)
        var.input_type = t
        var.sparse_seq = True
        ctx = _ctx()
        ctx.data_layers.append(var)
        return var
    var = v2l.data(name, t)
    var.height, var.width = height, width
    _ctx().data_layers.append(var)
    return var


def _sparse_seq_fc_branch(inp, size, param_attr):
    """fc over a sequence of sparse binary vectors: per-timestep
    embedding-sum. ids [b, T, K] are K-padded with -1; the pad mask zeroes
    their contribution so the result equals each timestep's multi-hot row
    @ W exactly."""
    t = inp.input_type
    ids = L.relu(inp)  # clamp the -1 pads to a valid lookup id
    emb = L.embedding(ids, size=[t.dim, size], param_attr=_pa(param_attr))
    mask = L.cast(L.greater_equal(
        inp, L.fill_constant(shape=[1], value=0, dtype=inp.dtype)),
        "float32")
    emb = L.elementwise_mul(emb, L.reshape(mask, shape=[0, 0, -1, 1]))
    summed = L.reduce_sum(emb, dim=-2)
    summed.seq_len = inp.seq_len
    return summed


def fc_layer(input, size, act=None, param_attr=None, bias_attr=None, **kw):
    inputs_ = input if isinstance(input, (list, tuple)) else [input]
    sparse_seq = [v for v in inputs_ if getattr(v, "sparse_seq", False)]
    rest = [v for v in inputs_ if not getattr(v, "sparse_seq", False)]
    if isinstance(bias_attr, ParamAttr):
        bias_attr = bias_attr.to_fluid()
    if not sparse_seq:
        return _group_register_name(kw.get("name"), _maybe_drop(
            v2l.fc(input if isinstance(input, (list, tuple)) and
                   len(inputs_) > 1 else inputs_[0], size, act=act,
                   param_attr=_pa(param_attr), bias_attr=bias_attr), kw))
    from ..layers.layer_helper import LayerHelper

    branches = [_sparse_seq_fc_branch(v, size, param_attr)
                for v in sparse_seq]
    if rest:
        # a [b, size] dense branch cannot broadcast onto the [b, T, size]
        # per-timestep branches
        raise ValueError("fc over mixed sparse-sequence and plain inputs "
                         "is not supported")
    summed = branches[0] if len(branches) == 1 else L.addto(branches,
                                                            act=None)
    helper = LayerHelper("fc")
    seq_len = branches[0].seq_len
    if bias_attr is not False:
        summed = helper.append_bias_op(summed, bias_attr, size,
                                       dim_start=len(summed.shape) - 1)
    summed = helper.append_activation(summed, _act.resolve(act))
    summed.seq_len = seq_len
    return summed


def embedding_layer(input, size, param_attr=None, **kw):
    return _maybe_drop(v2l.embedding(input, size, param_attr=_pa(param_attr)),
                       kw)


# -- mixed_layer + projections (reference layers.py mixed_layer et al.) ----
# The builders live in the v2 facade; these shims translate v1 ParamAttr
# objects at the boundary so reference configs pass them unchanged.

def full_matrix_projection(input, size=0, param_attr=None, **kw):
    return v2l.full_matrix_projection(input, size=size,
                                      param_attr=_pa(param_attr))


def trans_full_matrix_projection(input, size=0, param_attr=None, **kw):
    return v2l.trans_full_matrix_projection(input, size=size,
                                            param_attr=_pa(param_attr))


def table_projection(input, size=0, param_attr=None, **kw):
    return v2l.table_projection(input, size=size, param_attr=_pa(param_attr))


def identity_projection(input, offset=None, size=None, **kw):
    return v2l.identity_projection(input, offset=offset, size=size)


def scaling_projection(input, param_attr=None, **kw):
    return v2l.scaling_projection(input, param_attr=_pa(param_attr))


def dotmul_projection(input, param_attr=None, **kw):
    return v2l.dotmul_projection(input, param_attr=_pa(param_attr))


def context_projection(input, context_len, context_start=None, **kw):
    return v2l.context_projection(input, context_len,
                                  context_start=context_start)


def mixed_layer(size=0, input=None, act=None, bias_attr=None, **kw):
    """v1 mixed_layer: immediate form (input=[projections]) or context
    manager collecting ``+=`` projections. Reference defaults: NO bias
    unless bias_attr is set (wrap_bias_attr_default(has_bias=False),
    layers.py:865); layer_attr=ExtraAttr(drop_rate=...) applies dropout
    in both forms."""
    if isinstance(bias_attr, ParamAttr):
        bias_attr = bias_attr.to_fluid()
    elif bias_attr is True:
        bias_attr = None  # default bias
    elif bias_attr is None:
        bias_attr = False  # reference default: no bias
    rate = getattr(kw.get("layer_attr"), "drop_rate", None) or 0.0
    out = v2l.mixed_layer(size=size, input=input, act=act,
                          bias_attr=bias_attr, drop_rate=rate)
    if input is not None:
        _group_register_name(kw.get("name"), out)
    return out


def recurrent_layer(input, act=None, bias_attr=None, param_attr=None,
                    reverse=False, **kw):
    """v1 recurrent_layer (reference layers.py recurrent_layer ->
    gserver RecurrentLayer.cpp): out_t = act(in_t + out_{t-1} @ W + b);
    the input is already at the layer's width."""
    # act unset -> tanh (reference wrap_act_default); an EXPLICIT
    # LinearActivation (whose resolved name is empty) means the identity
    # recurrence, not the default.
    act_name = "tanh" if act is None else (_act.resolve(act) or "identity")
    if isinstance(bias_attr, ParamAttr):
        bias_attr = bias_attr.to_fluid()
    elif bias_attr is True:
        bias_attr = None  # default bias
    o = L.simple_rnn(input, is_reverse=reverse, activation=act_name,
                     param_attr=_pa(param_attr), bias_attr=bias_attr)
    return _maybe_drop(o, kw)


def img_conv_layer(input, filter_size, num_filters, num_channels=None,
                   stride=1, padding=0, groups=1, act=None, param_attr=None,
                   bias_attr=None, **kw):
    input = _as_image(input, num_channels)
    return _group_register_name(kw.get("name"), v2l.img_conv(
        input, filter_size, num_filters, num_channels=num_channels,
        stride=stride, padding=padding, groups=groups, act=act,
        param_attr=_pa(param_attr), bias_attr=_pa(bias_attr)))


def img_pool_layer(input, pool_size, stride=1, padding=0, pool_type=None,
                   num_channels=None, ceil_mode=True, **kw):
    return _group_register_name(kw.get("name"), v2l.img_pool(
        _as_image(input, num_channels), pool_size, stride=stride,
        padding=padding, pool_type=pool_type, ceil_mode=ceil_mode))


def batch_norm_layer(input, act=None, use_global_stats=None, **kw):
    if use_global_stats is not None:
        kw.setdefault("is_test", bool(use_global_stats))
    return _group_register_name(kw.get("name"),
                                v2l.batch_norm(input, act=act, **kw))


def dropout_layer(input, dropout_rate=0.5, **kw):
    return v2l.dropout(input, dropout_rate)


def pooling_layer(input, pooling_type=None, **kw):
    return v2l.pooling(input, pooling_type)


def concat_layer(input, **kw):
    return v2l.concat(input)


def addto_layer(input, act=None, **kw):
    return _group_register_name(kw.get("name"), v2l.addto(input, act=act))


def maxid_layer(input, **kw):
    return v2l.max_id(input)


def lstmemory(input, size=None, reverse=False, act=None, **kw):
    return _maybe_drop(v2l.lstmemory(input, size=size, reverse=reverse), kw)


def grumemory(input, size=None, reverse=False, **kw):
    return _maybe_drop(v2l.grumemory(input, size=size, reverse=reverse), kw)


def first_seq(input, **kw):
    return v2l.first_seq(input)


def last_seq(input, **kw):
    return v2l.last_seq(input)


def crf_layer(input, label, size=None, param_attr=None, **kw):
    return L.linear_chain_crf(input, label, param_attr=_pa(param_attr))


def crf_decoding_layer(input, size=None, label=None, param_attr=None,
                       **kw):
    return L.crf_decoding(input, param_attr=_pa(param_attr), label=label)


def classification_cost(input, label, name=None, **kw):
    return v2l.classification_cost(input, label)


def cross_entropy(input, label, **kw):
    return v2l.cross_entropy_cost(input, label)


def regression_cost(input, label, **kw):
    return v2l.square_error_cost(input, label)


mse_cost = regression_cost


def _as_image(var, num_channels=None):
    """v1 image layers consume flat [C*H*W] data vectors; reshape to NHWC
    when needed (the reference config_parser infers H=W=sqrt(size/C),
    config_parser.py parse_image)."""
    shape = [int(d) for d in var.shape if d != -1]
    if len(shape) == 1 and num_channels:
        hw = int(math.isqrt(shape[0] // num_channels))
        if hw * hw * num_channels != shape[0]:
            raise ValueError(
                f"cannot infer square image from size {shape[0]} with "
                f"{num_channels} channels")
        return L.reshape(var, shape=[-1, hw, hw, num_channels])
    return var


def img_conv_group(input, conv_num_filter, num_channels=None, pool_size=2,
                   pool_stride=2, conv_padding=1, conv_filter_size=3,
                   conv_act=None, conv_with_batchnorm=False,
                   conv_batchnorm_drop_rate=0.0, pool_type=None, **kw):
    """VGG-style group (reference trainer_config_helpers/networks.py
    img_conv_group): N convs (+BN (+dropout)) then one pool. Honors the
    v1 conv_padding contract (the fluid nets version always same-pads)."""
    n = len(conv_num_filter)

    def per(x):
        return list(x) if isinstance(x, (list, tuple)) else [x] * n

    pads = per(conv_padding)
    sizes = per(conv_filter_size)
    with_bn = per(conv_with_batchnorm)
    drops = per(conv_batchnorm_drop_rate)
    tmp = _as_image(input, num_channels)
    for i in range(n):
        tmp = v2l.img_conv(tmp, sizes[i], conv_num_filter[i],
                           stride=1, padding=pads[i],
                           act=None if with_bn[i] else conv_act)
        if with_bn[i]:
            tmp = v2l.batch_norm(tmp, act=conv_act)
            if drops[i] > 0:
                tmp = v2l.dropout(tmp, drops[i])
    return v2l.img_pool(tmp, pool_size, stride=pool_stride,
                        pool_type=pool_type)


def simple_img_conv_pool(input, filter_size, num_filters, pool_size,
                         pool_stride=1, act=None, num_channel=None,
                         pool_type=None, groups=1, conv_stride=1,
                         conv_padding=0, bias_attr=None, param_attr=None,
                         pool_padding=0, **kw):
    """conv -> pool with the REFERENCE defaults (networks.py:144
    simple_img_conv_pool: conv_padding=0, conv_stride=1, pool_padding=0)
    so unmodified v1 configs get the reference's output geometry and
    parameter shapes."""
    tmp = img_conv_layer(input, filter_size, num_filters,
                         num_channels=num_channel, stride=conv_stride,
                         padding=conv_padding, groups=groups, act=act,
                         param_attr=param_attr, bias_attr=bias_attr)
    return v2l.img_pool(tmp, pool_size, stride=pool_stride,
                        padding=pool_padding,
                        pool_type=pool_type or MaxPooling())


# -- trainer_config_helpers/networks.py composites -------------------------

def simple_lstm(input, size, reverse=False, **kw):
    from ..v2 import networks as _nets

    return _nets.simple_lstm(input, size, reverse=reverse)


def bidirectional_lstm(input, size, return_seq=False, **kw):
    """reference networks.py bidirectional_lstm: fwd+bwd simple_lstm.
    return_seq=False returns the concat of the two LAST states (the
    text-classification head); True the concatenated sequences."""
    from ..v2 import networks as _nets

    if return_seq:
        return _nets.bidirectional_lstm(input, size, return_concat=True)
    fwd, bwd = _nets.bidirectional_lstm(input, size, return_concat=False)
    for v in (fwd, bwd):
        if getattr(v, "seq_len", None) is None:
            v.seq_len = getattr(input, "seq_len", None)
    return L.concat([L.sequence_last_step(fwd),
                     L.sequence_first_step(bwd)], axis=-1)


def simple_gru(input, size, reverse=False, **kw):
    from ..v2 import networks as _nets

    return _nets.simple_gru(input, size, reverse=reverse)


def bidirectional_gru(input, size, **kw):
    from ..v2 import networks as _nets

    return _nets.bidirectional_gru(input, size)


def small_vgg(input_image, num_channels=None, num_classes=10, **kw):
    from ..v2 import networks as _nets

    img = _as_image(input_image, num_channels)
    return _nets.small_vgg(img, num_classes=num_classes)


def vgg_16_network(input_image, num_channels=None, num_classes=1000,
                   **kw):
    from ..v2 import networks as _nets

    img = _as_image(input_image, num_channels)
    return _nets.vgg_16_network(img, num_classes=num_classes)


def text_conv_pool(input, context_len=5, hidden_size=128, **kw):
    from ..v2 import networks as _nets

    return _nets.text_conv_pool(input, context_len=context_len,
                                hidden_size=hidden_size)


def sequence_conv_pool(input, context_len, hidden_size, **kw):
    from ..v2 import networks as _nets

    return _nets.sequence_conv_pool(input, context_len, hidden_size)


def simple_attention(encoded_sequence, encoded_proj, decoder_state, **kw):
    from ..v2 import networks as _nets

    return _nets.simple_attention(encoded_sequence, encoded_proj,
                                  decoder_state)


def sum_cost(input, **kw):
    return v2l.sum_cost(input)


def smooth_l1_cost(input, label, **kw):
    return v2l.smooth_l1_cost(input, label)


def huber_classification_cost(input, label, **kw):
    return v2l.huber_classification_cost(input, label)


def multi_binary_label_cross_entropy(input, label, **kw):
    return v2l.multi_binary_label_cross_entropy(input, label)


class _LayerMath:
    """The ``layer_math`` namespace (reference trainer_config_helpers/
    layer_math.py): unary math as layers. Binary arithmetic rides the
    repo's Variable operator overloading (layers/math_op_patch.py), the
    same contract the reference implements with LayerOutput operators."""

    @staticmethod
    def _unary(op_name):
        def op(input, name=None, **kw):
            from ..layers.layer_helper import LayerHelper

            helper = LayerHelper(op_name)
            return _group_register_name(
                name, helper.simple_op(op_name, {"X": [input]}, {}))

        op.__name__ = op_name
        return op


layer_math = _LayerMath()
for _un in ("exp", "log", "abs", "sigmoid", "tanh", "square", "relu",
            "sqrt", "reciprocal"):
    setattr(layer_math, _un, _LayerMath._unary(_un))
del _un


# ---------------------------------------------------------------------------
# the step-level recurrent DSL: recurrent_group / memory / StaticInput /
# gru_step_layer / lstm_step_layer (reference layers.py recurrent_group ->
# gserver RecurrentGradientMachine.h:32). TPU-first: the step function is
# traced ONCE into a StaticRNN sub-block and the whole group lowers to a
# single lax.scan — no per-step sub-network instantiation.
# ---------------------------------------------------------------------------

class StaticInput:
    """Wrap a non-sequence (or whole-sequence, for attention) input that
    every step sees in full (reference layers.py StaticInput)."""

    def __init__(self, input, is_seq=False, size=None):
        self.input = input
        self.is_seq = is_seq


class GeneratedInput:
    """Accepted for source compatibility; in-config generation through
    recurrent_group is NOT the TPU path — beam/greedy generation runs
    through the in-graph decode ops instead (models.transformer_lm_*,
    layers.beam_search_decoder; see STATUS.md row 29)."""

    def __init__(self, size=0, embedding_name=None, embedding_size=0,
                 **kw):
        raise NotImplementedError(
            "GeneratedInput (in-config beam generation) is served by the "
            "in-graph decode ops: models.transformer_lm_generate / "
            "_beam_search, layers.beam_search_decoder")


class _GroupState:
    def __init__(self, rnn, first_seq):
        self.rnn = rnn
        self.first_seq = first_seq
        self.memories = []       # (mem_var, v1 name)
        self.named_outputs = {}  # v1 layer name -> produced var


_GROUP: Optional[_GroupState] = None


def _group_register_name(name, var):
    """Layer shims call this so memory(name=...) can link to a step
    layer produced under that name (the reference's name-based memory
    wiring), and so Outputs("name") can resolve layers by their v1
    name at parse end."""
    if name:
        if _GROUP is not None:
            # step-internal names stay group-scoped: they denote scan
            # sub-block vars the main program never produces, so they
            # must not shadow/poison the Outputs() registry
            _GROUP.named_outputs[name] = var
        elif _CTX is not None:
            _CTX.named_layers[name] = var
    return var


def memory(name=None, size=0, boot_layer=None, is_seq=False, **kw):
    """The step-scope memory: this step reads the PREVIOUS step's value
    of the layer named ``name`` (or of whatever updates it via
    output_mem). boot_layer (or zeros [b, size]) seeds t=0."""
    grp = _GROUP
    if grp is None:
        raise RuntimeError("memory() is only valid inside a "
                           "recurrent_group step function")
    rnn = grp.rnn
    if boot_layer is None:
        # synthesize the zeros boot in the PARENT block (MemInit must be
        # an outer var, not a body op output)
        prog = rnn.helper.main_program
        cur = prog.current_block_idx
        prog.current_block_idx = prog.blocks[cur].parent_idx
        try:
            boot = L.fill_constant_batch_size_like(
                input=grp.first_seq, shape=[-1, int(size)],
                value=0.0, dtype="float32")
        finally:
            prog.current_block_idx = cur
    else:
        boot = boot_layer
    mem = rnn.memory(init=boot)
    grp.memories.append((mem, name))
    return mem


def gru_step_layer(input, output_mem, size=None, act=None,
                   gate_act=None, name=None, param_attr=None,
                   bias_attr=None, **kw):
    """One GRU step inside a recurrent_group (reference gru_step_layer):
    ``input`` is the pre-projected [b, 3h] slice, ``output_mem`` the
    state memory — updated with the new hidden, which is returned."""
    grp = _GROUP
    if grp is None:
        raise RuntimeError("gru_step_layer is only valid inside a "
                           "recurrent_group step function")
    size = int(size or output_mem.shape[-1])
    h, _, _ = L.gru_unit(
        input, output_mem, size,
        activation=_act.resolve(act) or "tanh",
        gate_activation=_act.resolve(gate_act) or "sigmoid",
        param_attr=_pa(param_attr), bias_attr=bias_attr)
    grp.rnn.update_memory(output_mem, h)
    return _group_register_name(name, h)


def lstm_step_layer(input, state, size=None, act=None, gate_act=None,
                    state_act=None, name=None, bias_attr=None, **kw):
    """One LSTM step inside a recurrent_group (reference
    lstm_step_layer): ``input`` is the [b, 4h] gate pre-projection,
    ``state`` the CELL memory (updated in place); returns the hidden."""
    grp = _GROUP
    if grp is None:
        raise RuntimeError("lstm_step_layer is only valid inside a "
                           "recurrent_group step function")
    from ..layers.layer_helper import LayerHelper

    helper = LayerHelper("lstm_step")
    outs, _ = helper.append_op(
        "lstm_unit", {"X": [input], "C_prev": [state]}, ["C", "H"],
        {"forget_bias": 0.0})
    c_new, h = outs["C"][0], outs["H"][0]
    grp.rnn.update_memory(state, c_new)
    return _group_register_name(name, h)


def recurrent_group(step, input, reverse=False, name=None, **kw):
    """Run ``step`` over every timestep (reference layers.py
    recurrent_group): sequence inputs are sliced per step, StaticInput
    is seen whole, memory() carries state, and the step outputs
    re-assemble into sequences. Lowers to ONE lax.scan."""
    global _GROUP
    inputs_ = input if isinstance(input, (list, tuple)) else [input]
    seqs = [i for i in inputs_ if not isinstance(i, StaticInput)]
    if not seqs:
        raise ValueError("recurrent_group needs at least one sequence "
                         "input (wrap constants in StaticInput)")
    if reverse:
        rev = {id(s): L.sequence_reverse(s) for s in seqs}
    rnn = L.StaticRNN()
    prev = _GROUP
    with rnn.step():
        grp = _GroupState(rnn, seqs[0])
        _GROUP = grp
        try:
            args = []
            for i in inputs_:
                if isinstance(i, StaticInput):
                    args.append(i.input)  # whole tensor; body param
                else:
                    args.append(rnn.step_input(
                        rev[id(i)] if reverse else i))
            outs = step(*args)
            outs_list = (list(outs) if isinstance(outs, (list, tuple))
                         else [outs])
            # link memories that were not explicitly updated: by the v1
            # name wiring, else (single memory, single output) to the
            # step's output — the simple-RNN idiom
            for mem, mname in grp.memories:
                if rnn.mem_out.get(mem.name) is not None:
                    continue
                tgt = grp.named_outputs.get(mname)
                if tgt is None and len(grp.memories) == 1 \
                        and len(outs_list) == 1:
                    tgt = outs_list[0]
                if tgt is None:
                    raise ValueError(
                        f"recurrent_group: memory {mname!r} is never "
                        f"updated — produce a step layer with "
                        f"name={mname!r} or use "
                        f"gru_step_layer/lstm_step_layer")
                rnn.update_memory(mem, tgt)
            for o in outs_list:
                rnn.step_output(o)
        finally:
            _GROUP = prev
    result = rnn()
    if reverse:
        rs = result if isinstance(result, (list, tuple)) else [result]
        rs = [L.sequence_reverse(o) for o in rs]
        result = rs[0] if len(rs) == 1 else rs
    return result


def get_output_layer(input, arg_name="", **kw):
    """Accepted shim: the repo's step layers return their primary output
    directly and update their state memories in place, so there is no
    secondary-argument plumbing to unpack."""
    return input


# -- the v1 layer-name tail (thin shims over the v2 builders) --------------

def img_cmrnorm_layer(input, size=5, scale=0.0128, power=0.75, **kw):
    return _maybe_drop(v2l.img_cmrnorm(input, size=size, scale=scale,
                                       power=power), kw)


def img_conv3d_layer(input, filter_size, num_filters, num_channels=None,
                     stride=1, padding=0, groups=1, act=None,
                     param_attr=None, bias_attr=None, **kw):
    return v2l.img_conv3d(input, filter_size, num_filters,
                          num_channels=num_channels, stride=stride,
                          padding=padding, groups=groups, act=act,
                          param_attr=_pa(param_attr),
                          bias_attr=_pa(bias_attr))


def img_pool3d_layer(input, pool_size, stride=1, padding=0,
                     pool_type=None, **kw):
    return v2l.img_pool3d(input, pool_size, stride=stride,
                          padding=padding, pool_type=pool_type)


def sub_seq_layer(input, offsets, sizes, **kw):
    return v2l.sub_seq(input, offsets, sizes)


def switch_order_layer(input, reshape_axis=None, act=None, **kw):
    return v2l.switch_order(input, reshape_axis=reshape_axis, act=act)


def scale_sub_region_layer(input, indices, value=1.0, **kw):
    return v2l.scale_sub_region(input, indices, value=value)


def selective_fc_layer(input, select, size, act=None, param_attr=None,
                       bias_attr=None, **kw):
    return v2l.selective_fc(input, select, size, act=act,
                            param_attr=_pa(param_attr),
                            bias_attr=_pa(bias_attr))


def lambda_cost(input, score, NDCG_num=5, max_sort_size=-1, **kw):
    # reference order (trainer_config_helpers.layers.lambda_cost):
    # ``input`` = the model's score output, ``score`` = the ground-truth
    # relevance — forwarded positionally, NOT swapped
    return v2l.lambda_cost(input, score, NDCG_num=NDCG_num,
                           max_sort_size=max_sort_size)


def cross_entropy_with_selfnorm(input, label,
                                softmax_selfnorm_alpha=0.1, **kw):
    return v2l.cross_entropy_with_selfnorm(
        input, label, softmax_selfnorm_alpha=softmax_selfnorm_alpha)


def conv_projection(input, filter_size, num_filters, stride=1, padding=0,
                    groups=1, param_attr=None, **kw):
    return v2l.conv_projection(input, filter_size, num_filters,
                               stride=stride, padding=padding,
                               groups=groups, param_attr=_pa(param_attr))


def dotmul_operator(a=None, b=None, scale=1.0, **kw):
    """dotmul_operator (reference layers.py DotMulOperator): the
    elementwise product of TWO layer outputs, scale-weighted, usable
    inside mixed_layer."""
    class _DotMulOp(v2l.BaseProjection):
        def __init__(self, x, y, scale):
            super().__init__(x)
            self.y = y
            self.scale = scale

        def build(self, size):
            out = L.elementwise_mul(self.input, self.y)
            if self.scale != 1.0:
                out = L.scale(out, self.scale)
            return out

    x = a if a is not None else kw.get("x")
    y = b if b is not None else kw.get("y")
    return _DotMulOp(x, y, float(scale))


def conv_operator(img=None, filter=None, **kw):
    """The reference conv_operator convolves ``img`` with the OUTPUT of
    the ``filter`` layer (a dynamic, data-dependent filter —
    ConvOperator.cpp). That form has no users in the reference's demos
    or benchmarks and no XLA-idiomatic analogue worth carrying; learned
    static-filter convolutions inside mixed_layer are conv_projection."""
    raise NotImplementedError(
        "conv_operator (dynamic data-dependent conv filters) is not "
        "supported; use conv_projection for learned-filter convolution "
        "projections")


def img_conv_bn_pool(input, filter_size, num_filters, pool_size,
                     num_channel=None, conv_padding=0, conv_stride=1,
                     pool_stride=1, act=None, pool_type=None,
                     drop_rate=0.0, groups=1, **kw):
    """conv -> BN(+act) -> [dropout] -> pool with the REFERENCE
    defaults (networks.py:231: conv_padding=0, conv_stride=1,
    pool_stride=1)."""
    img = _as_image(input, num_channel)
    tmp = v2l.img_conv(img, filter_size, num_filters, stride=conv_stride,
                       padding=conv_padding, groups=groups, act=None)
    tmp = v2l.batch_norm(tmp, act=act)
    if drop_rate:
        tmp = v2l.dropout(tmp, drop_rate)
    return v2l.img_pool(tmp, pool_size, stride=pool_stride,
                        pool_type=pool_type)


def simple_gru2(input, size, reverse=False, **kw):
    from ..v2 import networks as _nets

    return _nets.simple_gru2(input, size, reverse=reverse)


def dot_product_attention(encoded_sequence, attended_sequence=None,
                          transformed_state=None, softmax_param_attr=None,
                          name=None, **kw):
    """reference networks.py:1498 signature: (encoded_sequence,
    attended_sequence, transformed_state, ...)."""
    from ..v2 import networks as _nets

    return _group_register_name(name, _nets.dot_product_attention(
        encoded_sequence, attending_sequence=transformed_state,
        attended_sequence=attended_sequence))


def multi_head_attention(query, key=None, value=None,
                         key_proj_size=None, value_proj_size=None,
                         head_num=8,
                         attention_type="dot-product attention",
                         softmax_param_attr=None, name=None, **kw):
    """reference networks.py:1580 signature (query, key, value,
    key_proj_size, value_proj_size, head_num, attention_type, ...):
    batched multi-head attention over the whole sequences — the
    TPU-first replacement for the per-step recurrent_group form. The
    qkv projections are sized by the layer (d_model-uniform), so the
    per-side proj sizes are accepted for source compat."""
    o = L.multi_head_attention(query, keys=key, values=value,
                               num_heads=int(head_num))
    return _group_register_name(name, o)


def img_separable_conv(input, num_channels, num_out_channels,
                       filter_size, stride=1, padding=0,
                       depth_multiplier=1, act=None, **kw):
    """Depthwise conv (groups == channels) + 1x1 pointwise conv
    (reference networks.py img_separable_conv)."""
    dw = img_conv_layer(input, filter_size,
                        num_channels * depth_multiplier,
                        num_channels=num_channels, stride=stride,
                        padding=padding, groups=num_channels, act=None,
                        bias_attr=False)
    return img_conv_layer(dw, 1, num_out_channels, stride=1, padding=0,
                          act=act)


def lstmemory_unit(input, out_memory=None, size=None, name=None,
                   param_attr=None, input_proj_bias_attr=None, **kw):
    """One LSTM step WITH its input projection, for use inside a
    recurrent_group (reference networks.py lstmemory_unit): mixed
    4h projection of [x_t, h_{t-1}] -> lstm_step_layer over the cell
    memory; returns the hidden (registered under ``name``)."""
    size = int(size or (input.shape[-1] // 4))
    base = name or "lstmemory_unit"
    h_mem = out_memory if out_memory is not None else memory(
        name=f"{base}.h", size=size)
    c_mem = memory(name=f"{base}.c", size=size)
    proj = fc_layer(input=[input, h_mem], size=4 * size,
                    param_attr=param_attr,
                    bias_attr=input_proj_bias_attr)
    h = lstm_step_layer(proj, state=c_mem, size=size,
                        name=f"{base}.h" if out_memory is None else name)
    return _group_register_name(name, h)


def lstmemory_group(input, size=None, name=None, reverse=False,
                    param_attr=None, **kw):
    """recurrent_group over lstmemory_unit (reference networks.py
    lstmemory_group) — unlike ``lstmemory`` (the monolithic scan op),
    the step is user-visible for mixing with attention etc."""
    size = int(size or (input.shape[-1] // 4))
    base = name or "lstmemory_group"

    def step(x_t):
        return lstmemory_unit(x_t, size=size, name=base,
                              param_attr=param_attr)

    return recurrent_group(step=step, input=input, reverse=reverse)


def gru_unit(input, size=None, name=None, gru_param_attr=None,
             act=None, gate_act=None, **kw):
    """One GRU step for use inside a recurrent_group (reference
    networks.py gru_unit): the state memory + gru_step_layer."""
    size = int(size or (input.shape[-1] // 3))
    base = name or "gru_unit"
    mem = memory(name=base, size=size)
    return gru_step_layer(input, output_mem=mem, size=size, act=act,
                          gate_act=gate_act, param_attr=gru_param_attr,
                          name=base)


def gru_group(input, size=None, name=None, reverse=False,
              gru_param_attr=None, **kw):
    """recurrent_group over gru_unit (reference networks.py
    gru_group)."""
    size = int(size or (input.shape[-1] // 3))
    base = name or "gru_group"

    def step(x_t):
        return gru_unit(x_t, size=size, name=base,
                        gru_param_attr=gru_param_attr)

    return recurrent_group(step=step, input=input, reverse=reverse)


# ---------------------------------------------------------------------------
# the complete reference layers.py __all__: every remaining v1 name maps
# onto its v2-facade / fluid cognate (thin keyword adapters; the math
# lives in the op registry). Names with structural markers or py2-era
# machinery get honest shims.
# ---------------------------------------------------------------------------

def _v1_delegate(target, seq_args=0):
    def shim(*a, **kw):
        name = kw.pop("name", None)
        kw.pop("layer_attr", None)
        for k in ("param_attr", "bias_attr"):
            if k in kw:
                kw[k] = _pa(kw[k])
        return _group_register_name(name, target(*a, **kw))

    shim.__name__ = getattr(target, "__name__", "v1_shim")
    shim.__doc__ = (f"v1 adapter over {target.__module__}."
                    f"{shim.__name__} (reference layers.py)")
    return shim


repeat_layer = _v1_delegate(v2l.repeat)
seq_reshape_layer = _v1_delegate(v2l.seq_reshape)
cos_sim = _v1_delegate(v2l.cos_sim)
l2_distance_layer = _v1_delegate(v2l.l2_distance)
hsigmoid = _v1_delegate(v2l.hsigmoid)
square_error_cost = _v1_delegate(v2l.square_error_cost)
seq_concat_layer = _v1_delegate(v2l.seq_concat)
expand_layer = _v1_delegate(v2l.expand)
scaling_layer = _v1_delegate(v2l.scaling)
power_layer = _v1_delegate(v2l.power)
interpolation_layer = _v1_delegate(v2l.interpolation)
bilinear_interp_layer = _v1_delegate(L.bilinear_interp)
trans_layer = _v1_delegate(v2l.trans)
rotate_layer = _v1_delegate(v2l.rotate)
sum_to_one_norm_layer = _v1_delegate(v2l.sum_to_one_norm)
row_l2_norm_layer = _v1_delegate(v2l.row_l2_norm)
conv_shift_layer = _v1_delegate(v2l.conv_shift)
sampling_id_layer = _v1_delegate(v2l.sampling_id)
slope_intercept_layer = _v1_delegate(v2l.slope_intercept)
linear_comb_layer = _v1_delegate(v2l.linear_comb)
convex_comb_layer = linear_comb_layer  # the reference aliases them
ctc_layer = _v1_delegate(v2l.ctc)
warp_ctc_layer = _v1_delegate(L.warpctc)
nce_layer = _v1_delegate(v2l.nce)
rank_cost = _v1_delegate(v2l.rank_cost)
huber_regression_cost = _v1_delegate(v2l.huber_regression_cost)
block_expand_layer = _v1_delegate(v2l.block_expand)
maxout_layer = _v1_delegate(v2l.maxout)
dot_prod_layer = _v1_delegate(v2l.dot_prod)
out_prod_layer = _v1_delegate(v2l.out_prod)
priorbox_layer = _v1_delegate(L.prior_box)
multibox_loss_layer = _v1_delegate(L.multibox_loss)
pad_layer = _v1_delegate(v2l.pad)
eos_layer = _v1_delegate(v2l.eos)
multiplex_layer = _v1_delegate(v2l.multiplex)
row_conv_layer = _v1_delegate(L.row_conv)
prelu_layer = _v1_delegate(v2l.prelu)
gated_unit_layer = _v1_delegate(v2l.gated_unit)
kmax_seq_score_layer = _v1_delegate(v2l.kmax_seq_score)
scale_shift_layer = _v1_delegate(v2l.scale_shift)
resize_layer = _v1_delegate(v2l.resize)
factorization_machine = _v1_delegate(v2l.factorization_machine)
def seq_slice_layer(input, starts=None, ends=None, name=None, **kw):
    """seq_slice_layer (reference layers.py:7039): slice [start, end)
    per row — starts=None means 0, ends=None means the row's length.
    Runs over the sub_seq op (offset + size form)."""
    from ..layers.layer_helper import LayerHelper

    helper = LayerHelper("seq_slice")
    T = int(input.shape[1])
    if starts is None:
        starts = L.fill_constant_batch_size_like(
            input=input, shape=[-1, 1], value=0, dtype="int64")
    if ends is None:
        sl = getattr(input, "seq_len", None)
        ends = (L.reshape(sl, shape=[-1, 1]) if sl is not None else
                L.fill_constant_batch_size_like(
                    input=input, shape=[-1, 1], value=T, dtype="int64"))
    sizes = L.elementwise_sub(ends, starts)
    outs, _ = helper.append_op(
        "sub_seq", {"X": [input], "Offsets": [starts], "Sizes": [sizes]},
        ["Out", "OutLength"], {})
    o = outs["Out"][0]
    o.seq_len = outs["OutLength"][0]
    return _group_register_name(name, o)


def sub_nested_seq_layer(input, selected_indices, name=None, **kw):
    """Select sub-sequences of a nested sequence (reference
    SubNestedSequenceLayer.cpp). The dense lod_level=2 plane is
    [b, S, T, d]; ``selected_indices`` [b, K] picks sub-sequences per
    row (negative = empty slot)."""
    from ..layers.layer_helper import LayerHelper

    helper = LayerHelper("sub_nested_seq")
    return _group_register_name(name, helper.simple_op(
        "sub_nested_seq",
        {"X": [input], "Indices": [selected_indices]}, {}))


class slice_projection(v2l.BaseProjection):
    """Concatenated feature slices (reference SliceProjection.cpp):
    slices=[(s0, e0), (s1, e1), ...] over the input's last dim."""

    def __init__(self, input, slices, **kw):
        super().__init__(input)
        self.slices = [(int(s), int(e)) for s, e in slices]

    def build(self, size):
        from ..layers.layer_helper import LayerHelper

        helper = LayerHelper("slice_projection")
        rank = len(self.input.shape)
        parts = [helper.simple_op(
            "slice", {"X": [self.input]},
            {"axes": [rank - 1], "starts": [s], "ends": [e]})
            for s, e in self.slices]
        return parts[0] if len(parts) == 1 else L.concat(parts, axis=-1)


gru_step_naive_layer = gru_step_layer  # one fused formulation here


def crop_layer(input, offset, axis=2, shape=None, name=None, **kw):
    """crop_layer (reference CropLayer.cpp): crop dims starting at
    ``axis`` by per-dim ``offset`` to ``shape``. The op takes full-rank
    offsets/shape attrs; leading dims pass through uncropped."""
    from ..layers.layer_helper import LayerHelper

    helper = LayerHelper("crop")
    in_shape = list(input.shape)
    rank = len(in_shape)
    offs = [0] * axis + [int(o) for o in offset]
    offs += [0] * (rank - len(offs))
    if shape is None:
        raise ValueError("crop_layer needs the target shape (the "
                         "reference's reference-input form is served by "
                         "passing that layer's static shape)")
    tgt = list(in_shape[:axis]) + [int(d) for d in shape]
    tgt += list(in_shape[len(tgt):])
    # batch dim: crop never touches it; the op slices from offsets
    tgt[0] = in_shape[0] if in_shape[0] != -1 else -1
    attrs = {"offsets": offs, "shape": [int(d) if d != -1 else -1
                                        for d in tgt]}
    return _group_register_name(
        name, helper.simple_op("crop", {"X": [input]}, attrs))
def clip_layer(input, min, max, name=None, **kw):  # noqa: A002
    """clip_layer (reference layers.py signature (input, min, max)):
    elementwise clamp over the clip op (ClipLayer.cpp)."""
    from ..layers.layer_helper import LayerHelper

    helper = LayerHelper("clip")
    return _group_register_name(name, helper.simple_op(
        "clip", {"X": [input]}, {"min": float(min), "max": float(max)}))


def spp_layer(input, pyramid_height=3, pool_type=None, name=None, **kw):
    """Spatial pyramid pooling (reference SpatialPyramidPoolLayer.cpp)
    over the spp op."""
    from ..layers.layer_helper import LayerHelper

    helper = LayerHelper("spp")
    # default max (the reference's); note the spp op currently always
    # max-pools regardless of the attr (ops/extra_ops.py) — the attr is
    # recorded so an avg-capable op picks it up
    ptype = "max" if pool_type is None else _pool.resolve(pool_type)
    return _group_register_name(name, helper.simple_op(
        "spp", {"X": [input]},
        {"pyramid_height": int(pyramid_height), "pooling_type": ptype}))


def roi_pool_layer(input, rois, pooled_width=7, pooled_height=7,
                   spatial_scale=1.0, name=None, **kw):
    """RoI pooling (reference ROIPoolLayer.cpp) over the roi_pool op."""
    from ..layers.layer_helper import LayerHelper

    helper = LayerHelper("roi_pool")
    return _group_register_name(name, helper.simple_op(
        "roi_pool", {"X": [input], "ROIs": [rois]},
        {"pooled_height": int(pooled_height),
         "pooled_width": int(pooled_width),
         "spatial_scale": float(spatial_scale)}))


def tensor_layer(a, b, size, act=None, param_attr=None, name=None, **kw):
    """Bilinear tensor product (reference TensorLayer.cpp):
    out[:, i] = a @ W_i @ b^T with W [size, dim_a, dim_b]."""
    from ..layers.layer_helper import LayerHelper

    helper = LayerHelper("tensor_product")
    out = helper.simple_op(
        "tensor_product",
        {"A": [a], "B": [b],
         "Weight": [helper.create_parameter(
             _pa(param_attr),
             shape=[int(size), int(a.shape[-1]), int(b.shape[-1])],
             dtype=a.dtype)]}, {})
    out = helper.append_activation(out, _act.resolve(act))
    return _group_register_name(name, out)


def cross_channel_norm_layer(input, param_attr=None, name=None, **kw):
    """SSD's Normalize (reference CrossChannelNormLayer.cpp): L2
    normalize across channels (NCHW axis 1), learned per-channel scale.
    Composed from existing ops — elementwise chains fuse under XLA."""
    from ..layers.layer_helper import LayerHelper

    helper = LayerHelper("cross_channel_norm")
    C = int(input.shape[1])
    sq = L.elementwise_mul(input, input)
    ssum = L.reduce_sum(sq, dim=1, keep_dim=True)
    eps = L.fill_constant(shape=[1], value=1e-10, dtype="float32")
    norm = helper.simple_op("sqrt", {"X": [L.elementwise_add(ssum, eps)]},
                            {})
    scale = helper.create_parameter(
        _pa(param_attr), shape=[C], dtype=input.dtype,
        default_initializer=ConstantInitializer(1.0))
    normalized = L.elementwise_div(input, norm)
    out = L.elementwise_mul(normalized, L.reshape(scale,
                                                  shape=[1, C, 1, 1]))
    return _group_register_name(name, out)


def detection_output_layer(input_loc, input_conf, priorbox,
                           prior_variance=None, num_classes=21,
                           nms_threshold=0.45, nms_top_k=400,
                           keep_top_k=200, confidence_threshold=0.01,
                           background_id=0, name=None, **kw):
    """SSD detection output (reference DetectionOutputLayer.cpp):
    decode the predicted loc offsets against the priors (box_coder),
    then score-threshold + NMS (the detection_output op).
    input_loc [b, n_box, 4] offsets; input_conf [b, n_box, n_cls]
    scores; priorbox [n_box, 4]."""
    from ..layers.layer_helper import LayerHelper

    decoded = L.box_coder(priorbox, input_loc,
                          prior_variance=prior_variance,
                          code_type="decode_center_size")
    helper = LayerHelper("detection_output")
    return _group_register_name(name, helper.simple_op(
        "detection_output",
        {"Scores": [input_conf], "Boxes": [decoded]},
        {"nms_threshold": float(nms_threshold),
         "nms_top_k": int(nms_top_k),          # per-class NMS candidates
         "keep_top_k": int(keep_top_k),        # global cross-class cap
         "score_threshold": float(confidence_threshold),
         "background_id": int(background_id)}))


def print_layer(input, name=None, **kw):
    """Accepted declaration: the reference prints layer values during
    training; here the evaluator record carries the request and the
    layer passes through unchanged (printing inside one compiled XLA
    program would force a host round-trip per step)."""
    inputs_ = input if isinstance(input, (list, tuple)) else [input]
    if _CTX is not None:
        _evaluator("value_printer", name=name, input=inputs_)
    return input


printer_layer = print_layer


class AggregateLevel:
    """Sequence aggregation levels (reference layers.py AggregateLevel).
    The dense [b, T(, S), d]+length representation makes the level a
    property of the INPUT's shape here; accepted for source compat."""

    TO_NO_SEQUENCE = EACH_SEQUENCE = "non-seq"
    TO_SEQUENCE = EACH_TIMESTEP = "seq"


class ExpandLevel:
    FROM_NO_SEQUENCE = FROM_SEQUENCE = "non-seq"
    FROM_TIMESTEP = "timestep"


class LayerType:
    """Accepted marker namespace (reference layers.py LayerType enum);
    the op registry is the type system here."""


LayerOutput = object  # isinstance checks in user code stay truthy-safe


def layer_support(*attrs):
    """Accepted no-op decorator (reference layer_support marks DROPOUT
    etc.; layer_attr handling is built into every shim here)."""
    def deco(fn):
        return fn

    return deco


class SubsequenceInput(StaticInput):
    """Nested-sequence step input: served by the dense [b, S, T, d]
    plane — inside a recurrent_group the step sees one [b, T, d]
    sub-sequence slice per outer step."""

    def __init__(self, input, **kw):
        super().__init__(input, is_seq=True)


BaseGeneratedInput = GeneratedInput


class BeamInput:
    """cross_entropy_over_beam's input record — the beam-training plane
    is deliberately served by the in-graph beam ops instead (see
    cross_entropy_over_beam)."""

    def __init__(self, candidate_scores=None, selected_candidates=None,
                 gold=None, **kw):
        self.candidate_scores = candidate_scores
        self.selected_candidates = selected_candidates
        self.gold = gold


def cross_entropy_over_beam(input=None, **kw):
    """Deliberate absence with guidance (STATUS.md): beam-level CE
    exists for the reference's recurrent_group beam TRAINING machinery
    (CrossEntropyOverBeam.cpp); beam decoding/training here runs through
    the in-graph beam ops (layers.beam_search_decoder,
    models.transformer_lm_beam_search) whose scores are pinned to
    independent full-forward log-probs."""
    raise NotImplementedError(
        "cross_entropy_over_beam is served by the in-graph beam plane: "
        "train with teacher-forced softmax_with_cross_entropy and decode "
        "with layers.beam_search_decoder / "
        "models.transformer_lm_beam_search")


def beam_search(step, input, bos_id, eos_id, beam_size, max_length=100,
                **kw):
    """In-config beam-search generation (reference layers.py
    beam_search over recurrent_group): deliberately served by the
    in-graph decode ops — see GeneratedInput."""
    raise NotImplementedError(
        "in-config beam_search is served by the in-graph decode ops: "
        "models.transformer_lm_beam_search / layers.beam_search_decoder")


# ---------------------------------------------------------------------------
# evaluators: record the declaration; the v1 trainer materializes them
# ---------------------------------------------------------------------------

def evaluator_base(input, type=None, name=None, **kw):
    """The reference's evaluator_base: record an arbitrary evaluator
    declaration by type string."""
    _evaluator(str(type or "custom"), name=name, input=input, **kw)


def _evaluator(kind, **kw):
    _ctx().evaluators.append({"kind": kind, **kw})


def sum_evaluator(input, name=None, **kw):
    _evaluator("sum", name=name, input=input)


def classification_error_evaluator(input, label, name=None, **kw):
    _evaluator("classification_error", name=name, input=input, label=label)


def chunk_evaluator(input, label=None, chunk_scheme=None,
                    num_chunk_types=None, name=None, **kw):
    _evaluator("chunk", name=name, input=input, label=label,
               chunk_scheme=chunk_scheme, num_chunk_types=num_chunk_types)


def auc_evaluator(input, label, name=None, **kw):
    _evaluator("auc", name=name, input=input, label=label)


def precision_recall_evaluator(input, label, name=None, **kw):
    _evaluator("precision_recall", name=name, input=input, label=label)


def pnpair_evaluator(input, label, query_id=None, weight=None, name=None,
                     **kw):
    """Positive-negative pair ranking evaluator (reference Evaluator.cpp
    PnpairEvaluator); materialized by evaluator.PnpairEvaluator."""
    _evaluator("pnpair", name=name, input=input, label=label,
               query_id=query_id, weight=weight)


def ctc_error_evaluator(input, label, name=None, **kw):
    """CTC edit-distance evaluator (reference CTCErrorEvaluator.cpp);
    materialized by evaluator.CTCErrorEvaluator."""
    _evaluator("ctc_error", name=name, input=input, label=label)


def column_sum_evaluator(input, name=None, **kw):
    _evaluator("column_sum", name=name, input=input)


def detection_map_evaluator(input, label, name=None,
                            overlap_threshold=0.5, background_id=0,
                            evaluate_difficult=False, ap_type="11point",
                            **kw):
    """Detection mAP (reference Evaluator.cpp detection map);
    materialized by evaluator.DetectionMAPEvaluator."""
    _evaluator("detection_map", name=name, input=input, label=label,
               overlap_threshold=overlap_threshold,
               background_id=background_id, ap_type=ap_type)


def value_printer_evaluator(input, name=None, **kw):
    _evaluator("value_printer", name=name, input=input)


def gradient_printer_evaluator(input, name=None, **kw):
    _evaluator("gradient_printer", name=name, input=input)


def maxid_printer_evaluator(input, name=None, **kw):
    _evaluator("maxid_printer", name=name, input=input)


def maxframe_printer_evaluator(input, name=None, **kw):
    _evaluator("maxframe_printer", name=name, input=input)


def seqtext_printer_evaluator(input, result_file=None, name=None, **kw):
    _evaluator("seqtext_printer", name=name, input=input,
               result_file=result_file)


def classification_error_printer_evaluator(input, label, name=None, **kw):
    _evaluator("classification_error_printer", name=name, input=input,
               label=label)


def _register_named(fn):
    """Wrap a layer shim so a name= kwarg registers the result in the
    Outputs()/memory name registry — the reference accepts name= on
    EVERY layer, not just the handful that consume it."""
    import functools

    @functools.wraps(fn)
    def wrapped(*a, **kw):
        out = fn(*a, **kw)
        nm = kw.get("name")
        if nm and hasattr(out, "name"):
            _group_register_name(nm, out)
        return out

    return wrapped


for _n in list(globals()):
    if (_n.endswith("_layer") or _n in ("lstmemory", "grumemory",
                                        "mixed_layer", "first_seq",
                                        "last_seq", "classification_cost",
                                        "cross_entropy", "regression_cost",
                                        "lambda_cost",
                                        "cross_entropy_with_selfnorm",
                                        "img_conv_group",
                                        "simple_img_conv_pool")):
        _f = globals()[_n]
        if callable(_f) and not isinstance(_f, type):
            globals()[_n] = _register_named(_f)
del _n, _f


xrange = range  # py2-era reference configs iterate with xrange


# everything a `from paddle.trainer_config_helpers import *` should see
_EXPORTS = [n for n in dir() if not n.startswith("_")
            and n not in ("annotations", "importlib", "math", "os", "sys",
                          "Optional")]

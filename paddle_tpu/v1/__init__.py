"""v1 compatibility layer: the reference's first-generation user API.

Three pieces, matching how a 2017 reference user worked
(/root/reference/v1_api_demo/*):

- :func:`parse_config` — evaluate a trainer-config python file (the
  ``from paddle.trainer_config_helpers import *`` DSL) into Programs
  (config_parser.py; reference trainer/config_parser.py:4345).
- :mod:`~paddle_tpu.v1.data_provider` — the PyDataProvider2 ``@provider``
  decorator + input-type declarations provider modules import.
- :func:`train_from_config` — the ``paddle_trainer --config=...``
  equivalent: provider-fed batched training of the parsed config.

Import shims for the ``paddle.trainer_config_helpers`` /
``paddle.trainer.PyDataProvider2`` module names are installed on first
parse (only when no real ``paddle`` package exists), so unmodified
reference config + provider files run as-is.
"""
from . import data_provider
from .config_parser import ParsedConfig, parse_config
from .helpers import ParseContext
from .trainer import V1DataFeeder, make_reader, train_from_config

__all__ = ["parse_config", "ParsedConfig", "ParseContext", "data_provider",
           "train_from_config", "make_reader", "V1DataFeeder"]

"""parse_config — evaluate a v1 trainer config file into Programs.

The reference's first user API is a Python config file evaluated by
``parse_config`` (/root/reference/python/paddle/trainer/config_parser.py:
4345, driven from C++ via TrainerConfigHelper.cpp:34-59) under the
trainer_config_helpers DSL, producing a ModelConfig proto the trainer
consumes. Here the same evaluation produces the repo's Program pair plus
the config-level records (settings, data sources, inputs/outputs,
evaluators) that :mod:`paddle_tpu.v1.trainer` consumes.

Because reference config files open with
``from paddle.trainer_config_helpers import *`` (and provider modules with
``from paddle.trainer.PyDataProvider2 import *``), importable shim modules
under the ``paddle`` name are installed on first use — only when no real
``paddle`` package is present — so unmodified reference config files
execute as-is.
"""
from __future__ import annotations

import os
import sys
import types

from ..core.program import Program, program_guard
from . import data_provider as _dp
from . import helpers as _h


def _install_shims():
    """Make ``paddle.trainer_config_helpers`` / ``paddle.trainer.
    PyDataProvider2`` importable, pointing at the v1 compat modules."""
    if "paddle" in sys.modules:
        have = sys.modules["paddle"]
        if not getattr(have, "__paddle_tpu_v1_shim__", False):
            return  # a real paddle is installed; leave it alone
    try:
        import paddle  # noqa: F401 - a real installation wins
        return
    except ImportError:
        pass
    paddle = types.ModuleType("paddle")
    paddle.__paddle_tpu_v1_shim__ = True
    tch = types.ModuleType("paddle.trainer_config_helpers")
    for name in _h._EXPORTS:
        setattr(tch, name, getattr(_h, name))
    tch.__all__ = list(_h._EXPORTS)
    trainer = types.ModuleType("paddle.trainer")
    pdp2 = types.ModuleType("paddle.trainer.PyDataProvider2")
    for name in _dp.__all__:
        setattr(pdp2, name, getattr(_dp, name))
    pdp2.__all__ = list(_dp.__all__)
    paddle.trainer_config_helpers = tch
    paddle.trainer = trainer
    trainer.PyDataProvider2 = pdp2
    sys.modules["paddle"] = paddle
    sys.modules["paddle.trainer_config_helpers"] = tch
    sys.modules["paddle.trainer"] = trainer
    sys.modules["paddle.trainer.PyDataProvider2"] = pdp2


def _parse_config_args(config_arg_str):
    """'a=1,b=x' -> dict (reference config_parser.py parse_config)."""
    out = {}
    for part in (config_arg_str or "").split(","):
        part = part.strip()
        if not part:
            continue
        k, _, v = part.partition("=")
        out[k.strip()] = v.strip()
    return out


class ParsedConfig:
    """What parse_config returns: the built Program pair + config records.

    ``input_vars`` are the feed variables in the config's ``inputs()``
    order (creation order when inputs() was not called) — the order
    provider row tuples follow. ``output_vars`` are the ``outputs()``
    targets (training configs: the cost)."""

    def __init__(self, ctx, main_program, startup_program):
        self.main_program = main_program
        self.startup_program = startup_program
        self.settings = ctx.settings
        self.data_sources = ctx.data_sources
        self.evaluators = ctx.evaluators
        # Outputs("name") entries resolve against the v1 name registry
        self.output_vars = []
        for o in (ctx.outputs or []):
            if isinstance(o, str):
                if o not in ctx.named_layers:
                    raise ValueError(
                        f"Outputs({o!r}): no layer was created with "
                        f"name={o!r}; known names: "
                        f"{sorted(ctx.named_layers)[:20]}")
                o = ctx.named_layers[o]
            self.output_vars.append(o)
        by_name = {v.name: v for v in ctx.data_layers}
        order = ctx.inputs_order or [v.name for v in ctx.data_layers]
        self.input_vars = [by_name[n] for n in order if n in by_name]
        self.config_dir = ctx.config_dir
        # lazily-applied config-wide defaults (reference reads them at
        # parameter/optimizer build, so call order vs Settings is free)
        self.default_momentum = ctx.default_momentum
        self.default_decay_rate = ctx.default_decay_rate

    @property
    def cost(self):
        if not self.output_vars:
            raise ValueError("config declared no outputs()")
        return self.output_vars[0]

    def build_optimizer(self):
        """settings record -> a concrete optimizer, with the legacy
        gradient_clipping_threshold installed on the main program.
        String learning_methods (the Settings() form) and the
        default_momentum/default_decay_rate config-wide defaults resolve
        HERE, after the whole config evaluated (reference timing)."""
        method = _h.resolve_learning_method(
            self.settings.get("learning_method"),
            default_momentum=self.default_momentum)
        reg = self.settings.get("regularization")
        if reg is None and self.default_decay_rate:
            reg = _h.L2Regularization(self.default_decay_rate)
        opt = (method or _h.MomentumOptimizer(momentum=0.0)).build(
            self.settings.get("learning_rate", 0.01),
            regularization=reg)
        thr = self.settings.get("gradient_clipping_threshold")
        if thr:
            from ..clip import GradientClipByGlobalNorm, set_gradient_clip

            set_gradient_clip(GradientClipByGlobalNorm(thr),
                              program=self.main_program)
        return opt


def parse_config(config_file, config_arg_str=""):
    """Evaluate ``config_file`` (a v1 trainer config) and return a
    :class:`ParsedConfig`. ``config_arg_str`` is the reference's
    ``--config_args`` comma list, read inside the config via
    get_config_arg()."""
    _install_shims()
    config_file = os.fspath(config_file)
    with open(config_file) as fh:
        source = fh.read()
    ctx = _h.ParseContext(_parse_config_args(config_arg_str),
                          config_dir=os.path.dirname(
                              os.path.abspath(config_file)))
    main_program, startup_program = Program(), Program()
    ns = {name: getattr(_h, name) for name in _h._EXPORTS}
    ns["__file__"] = config_file
    ns["__name__"] = "__paddle_v1_config__"
    prev_ctx = _h._CTX
    _h._CTX = ctx
    added_path = ctx.config_dir not in sys.path
    if added_path:
        sys.path.insert(0, ctx.config_dir)
    had_maxint = hasattr(sys, "maxint")
    if not had_maxint:
        sys.maxint = sys.maxsize  # py2-era configs read sys.maxint
    try:
        with program_guard(main_program, startup_program):
            exec(compile(source, config_file, "exec"), ns)  # noqa: S102
    finally:
        _h._CTX = prev_ctx
        if not had_maxint:
            del sys.maxint
        if added_path and ctx.config_dir in sys.path:
            sys.path.remove(ctx.config_dir)
    if ctx.outputs is None and ctx.data_layers:
        raise ValueError(f"{config_file}: config declared no outputs()")
    return ParsedConfig(ctx, main_program, startup_program)

"""PyDataProvider2 compatibility surface.

Serves the decorator API reference data-provider modules are written
against (/root/reference/python/paddle/trainer/PyDataProvider2.py): the
``@provider`` decorator plus the input-type declaration functions. A
decorated process function becomes a :class:`DataProvider` object the v1
trainer (v1/trainer.py) drives: it instantiates a ``settings`` namespace,
runs the ``init_hook`` (which may fill ``settings.input_types``, the
reference's late-binding idiom), then iterates the generator per data
file.

The cache/pool knobs of the reference decorator are accepted for source
compatibility; only CACHE_PASS_IN_MEM changes behavior (rows of the first
pass are kept in memory, exactly the reference semantics — everything
else was thread-pool tuning for the C++ trainer and has no analogue in
this in-process reader).
"""
from __future__ import annotations

from ..v2.data_type import (InputType, dense_vector,  # noqa: F401
                            dense_vector_sequence, integer_value,
                            integer_value_sequence, sparse_binary_vector,
                            sparse_float_vector)

__all__ = [
    "provider", "DataProvider", "ProviderSettings", "CacheType",
    "dense_vector", "dense_array", "dense_vector_sequence",
    "integer_value", "integer_value_sequence", "integer_sequence",
    "sparse_binary_vector", "sparse_binary_vector_sequence",
    "sparse_float_vector", "sparse_float_vector_sequence",
]


def dense_array(dim):
    return dense_vector(dim)


def sparse_binary_vector_sequence(dim):
    """Per-timestep active-index lists (a row is [[ids...], [ids...], ...])."""
    return InputType(dim, 1, "int64", sparse="binary")


def sparse_float_vector_sequence(dim):
    return InputType(dim, 1, "int64", sparse="float")


# reference alias (PyDataProvider2.py: integer_sequence)
integer_sequence = integer_value_sequence


class CacheType:
    NO_CACHE = 0
    CACHE_PASS_IN_MEM = 1


class ProviderSettings:
    """The ``settings`` namespace handed to init_hook and the process
    generator. init_hook conventionally sets ``input_types`` and stashes
    whatever state process() needs (reference PyDataProvider2.py
    DataProvider.__init__)."""

    def __init__(self):
        self.input_types = None
        self.should_shuffle = None

    def __repr__(self):
        return f"ProviderSettings({sorted(self.__dict__)})"


class DataProvider:
    """What ``@provider`` returns: holds the generator + declaration."""

    def __init__(self, fn, input_types=None, init_hook=None,
                 cache=CacheType.NO_CACHE, **kw):
        self.fn = fn
        self.input_types = input_types
        self.init_hook = init_hook
        self.cache = cache
        self.extra = kw
        self.__name__ = getattr(fn, "__name__", "provider")

    def create(self, file_list=None, **args):
        """Instantiate settings (running init_hook with the
        define_py_data_sources2 ``args``); returns the settings object.
        After this, ``input_types`` is resolved (dict keyed by data-layer
        name, or a positional list). ``file_list`` is always passed to
        the hook — the reference contract (PyDataProvider2.py:434:
        init_hook(settings, file_list, **kwargs))."""
        settings = ProviderSettings()
        settings.input_types = self.input_types
        settings.file_list = list(file_list or [])
        if self.init_hook is not None:
            import inspect

            params = inspect.signature(self.init_hook).parameters
            takes_fl = ("file_list" in params
                        or any(p.kind is inspect.Parameter.VAR_KEYWORD
                               for p in params.values()))
            if takes_fl:
                self.init_hook(settings, file_list=settings.file_list,
                               **args)
            else:
                # hooks written against the repo's pre-file_list contract
                self.init_hook(settings, **args)
        return settings

    def __call__(self, settings, filename, *a, **kw):
        """Direct generator access (the undecorated call signature)."""
        return self.fn(settings, filename, *a, **kw)


def provider(input_types=None, init_hook=None, cache=CacheType.NO_CACHE,
             pool_size=-1, min_pool_size=-1, can_over_batch_size=True,
             calc_batch_size=None, check=False, check_fail_continue=False,
             should_shuffle=None, **kw):
    """The PyDataProvider2 decorator. Accepts the full reference keyword
    surface; returns a :class:`DataProvider`. Also usable bare
    (``@provider`` without parentheses)."""
    if callable(input_types) and init_hook is None:  # bare @provider
        return DataProvider(input_types)

    def wrap(fn):
        return DataProvider(fn, input_types=input_types,
                            init_hook=init_hook, cache=cache,
                            should_shuffle=should_shuffle, **kw)

    return wrap

"""Publisher: roll fresh trainer checkpoints into a live serving fleet.

The last hop of the online-learning loop: watch the
:class:`StreamingTrainer`'s checkpoint directory and, whenever a new
intact generation lands, drive :meth:`Fleet.update_weights` — the PR 9
rolling swap (drain -> same-signature hot-swap -> warm-verify ->
rejoin), so the fleet serves throughout, pays zero recompiles, and KV
caches are invalidated where they must be.

One generation is published CONSISTENTLY: the checkpoint is loaded once
into a pinned array source and every replica swaps from that same dict
— a trainer save landing mid-roll cannot split the fleet across two
generations (it publishes on the next poll). Fleets with remote
(HttpReplica) members fall back to passing the directory path, which
their ``/admin/swap`` loads server-side.

Freshness is a first-class signal: ``weights_version`` /
``weights_staleness_s`` / ``weights_age_s`` gauges land in the fleet's
MetricsRegistry (→ ``/metrics``, ``/fleet/status``, ``fleetctl
status``), and an :class:`~paddle_tpu.trace.slo.SLO` with
``freshness_s`` set turns seconds-behind-trainer into a burn-rate-
tracked objective next to TTFT/availability.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Optional

from .. import checkpoint as ckpt_mod
from .. import trace


class _PinnedGeneration(dict):
    """One checkpoint generation as an array dict (what swap_params
    consumes), with a readable repr for spans/results."""

    def __init__(self, arrays, dirname: str, step: int):
        super().__init__(arrays)
        self.dirname = dirname
        self.step = step

    def __str__(self):
        return f"{self.dirname}@step-{self.step}"

    __repr__ = __str__


class Publisher:
    """Watch a checkpoint dir; publish new generations into a fleet.

    fleet:       a :class:`paddle_tpu.serving.fleet.Fleet` (the
                 publisher attaches itself as ``fleet.publisher`` so
                 ``/fleet/status`` grows the ``weights`` block).
    dirname:     the trainer's checkpoint directory.
    poll_s:      watch cadence of the background thread (:meth:`start`);
                 :meth:`poll_once` is the same logic inline.
    verify:      forward to ``update_weights`` (warm-manifest verify).
    min_interval_s: publish rate limit — generations landing faster
                 than this coalesce (the newest wins).
    accept:      optional meta predicate (``checkpoint.load_checkpoint``
                 ``accept=`` semantics): only generations it passes are
                 publishable — e.g. reject a lineage whose writer token
                 the master fenced, so a zombie's generation never
                 reaches the serving fleet.
    pin:         pin the published generation against the trainer's
                 retention GC (``checkpoint.pin_generation``) so the
                 weights production is serving survive ``keep_last_n``
                 pruning — a replica restart can always re-load them
                 (default True).
    tenant:      scope every publish to ONE resident model on
                 multi-tenant replicas: rolls go through
                 ``update_weights(tenant=...)`` (only that tenant
                 drains; the others serve through it) and the freshness
                 gauges become labeled series
                 (``weights_version{tenant=...}``). One Publisher per
                 tenant rolls each model independently.
    """

    def __init__(self, fleet, dirname: str, poll_s: float = 0.25,
                 verify: bool = True, min_interval_s: float = 0.0,
                 accept=None, pin: bool = True,
                 tenant: Optional[str] = None):
        self.fleet = fleet
        self.dirname = str(dirname)
        self.poll_s = float(poll_s)
        self.verify = bool(verify)
        self.min_interval_s = float(min_interval_s)
        self.accept = accept
        self.pin = bool(pin)
        self.tenant = tenant
        self.published_step: Optional[int] = None
        self.published_ckpt_time: Optional[float] = None
        self.generations = 0          # successful publishes
        self.skipped = 0              # discovered-then-GC'd races skipped
        self.last_publish_s: Optional[float] = None  # roll wall time
        self.last_error: Optional[str] = None
        self._published_at: Optional[float] = None   # monotonic-ish
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._lock = threading.Lock()
        if tenant is None:
            fleet.publisher = self
        else:
            # tenant-scoped publishers register per name; the untenanted
            # fleet.publisher slot stays for the single-model fleet shape
            if not hasattr(fleet, "tenant_publishers"):
                fleet.tenant_publishers = {}
            fleet.tenant_publishers[tenant] = self

    # -- watching --------------------------------------------------------
    def _ckpt_time(self, step: int) -> Optional[float]:
        """Wall-clock time the generation was written (the save's meta
        sidecar; payload mtime as fallback)."""
        payload = f"ckpt-{step}.npz"
        info = ckpt_mod._step_info(self.dirname, payload)
        if info and info.get("timestamp"):
            return float(info["timestamp"])
        try:
            return os.path.getmtime(os.path.join(self.dirname, payload))
        except OSError:
            return None

    def latest_step(self) -> Optional[int]:
        return ckpt_mod.latest_step(self.dirname, accept=self.accept)

    def staleness_s(self) -> float:
        """Seconds the SERVED weights are behind the trainer's newest
        intact generation: 0 while caught up, else the age of the
        newest checkpoint the fleet is not serving yet."""
        latest = self.latest_step()
        if latest is None or latest == self.published_step:
            return 0.0
        ts = self._ckpt_time(latest)
        return max(0.0, time.time() - ts) if ts else 0.0

    # -- publishing ------------------------------------------------------
    def _pinned_source(self, step: int):
        """Load the generation ONCE so every replica swaps identical
        arrays; remote replicas can only take a path (their /admin/swap
        loads server-side)."""
        from ..serving.fleet import HttpReplica

        if any(isinstance(rep, HttpReplica)
               for rep in self.fleet.replicas):
            return self.dirname
        from ..core.scope import Scope

        staging = Scope()
        meta = ckpt_mod.load_checkpoint(self.dirname, scope=staging,
                                        accept=self.accept)
        return _PinnedGeneration(
            {k: staging.get(k) for k in staging.keys()},
            self.dirname, int(meta.get("step", step)))

    def poll_once(self) -> Optional[int]:
        """Publish the newest unpublished generation, if any; returns
        the published step (None when already fresh / rate-limited /
        failed — failures land in ``last_error`` and the error counter,
        the fleet keeps serving the old weights)."""
        latest = self.latest_step()
        if latest is None or latest == self.published_step:
            self.refresh_gauges()
            return None
        if (self.min_interval_s and self._published_at is not None
                and time.monotonic() - self._published_at
                < self.min_interval_s):
            self.refresh_gauges()
            return None
        with self._lock:  # one roll at a time (thread + manual callers)
            t0 = time.monotonic()
            try:
                source = self._pinned_source(latest)
                step = getattr(source, "step", latest)
                with trace.span("online/publish", step=step,
                                dirname=self.dirname,
                                tenant=self.tenant or ""):
                    # the tenant kwarg only exists on tenant-aware fleets;
                    # the untenanted call shape stays byte-compatible
                    if self.tenant is None:
                        self.fleet.update_weights(source, verify=self.verify)
                    else:
                        self.fleet.update_weights(source, verify=self.verify,
                                                  tenant=self.tenant)
            except Exception as exc:  # noqa: BLE001 - keep serving old
                payload = os.path.join(self.dirname, f"ckpt-{latest}.npz")
                if isinstance(exc, FileNotFoundError) \
                        or not os.path.exists(payload):
                    # discovered-then-GC'd race: the trainer's retention
                    # pruned this generation between our latest_step()
                    # and the load — not an error, the NEXT poll sees a
                    # newer one. Skip with a counter; keep serving old.
                    self.skipped += 1
                    self.fleet.metrics.inc("weight_publish_skipped")
                    self.refresh_gauges()
                    return None
                self.last_error = f"{type(exc).__name__}: {exc}"
                self.fleet.metrics.inc("weight_publish_errors")
                self.refresh_gauges()
                return None
            self.last_publish_s = time.monotonic() - t0
            self.published_step = step
            self.published_ckpt_time = self._ckpt_time(step)
            self._published_at = time.monotonic()
            self.generations += 1
            self.last_error = None
            self.fleet.metrics.inc("weight_generations")
            if self.pin:
                # the serving fleet is live on this generation: retention
                # GC must never delete it, however old it grows
                try:
                    ckpt_mod.pin_generation(self.dirname, step)
                except OSError:
                    pass
            self.refresh_gauges()
            return step

    # -- observability ---------------------------------------------------
    def refresh_gauges(self) -> None:
        m = self.fleet.metrics
        if self.tenant is not None:
            # one freshness plane per tenant, as labeled series
            m.set_labeled("weights_version",
                          float(self.published_step or 0),
                          tenant=self.tenant)
            m.set_labeled("weights_staleness_s",
                          round(self.staleness_s(), 6),
                          tenant=self.tenant)
            if self.published_ckpt_time is not None:
                m.set_labeled(
                    "weights_age_s",
                    round(time.time() - self.published_ckpt_time, 6),
                    tenant=self.tenant)
            return
        m.set_gauge("weights_version", float(self.published_step or 0))
        m.set_gauge("weights_staleness_s", round(self.staleness_s(), 6))
        if self.published_ckpt_time is not None:
            m.set_gauge("weights_age_s",
                        round(time.time() - self.published_ckpt_time, 6))

    def status(self) -> dict:
        """The ``weights`` block of ``/fleet/status``."""
        return {
            "dirname": self.dirname,
            "tenant": self.tenant,
            "published_step": self.published_step,
            "latest_step": self.latest_step(),
            "staleness_s": round(self.staleness_s(), 6),
            "generations": self.generations,
            "skipped": self.skipped,
            "last_publish_s": self.last_publish_s,
            "last_error": self.last_error,
            "watching": self._thread is not None,
        }

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "Publisher":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._watch, name="paddle-tpu-publisher",
                daemon=True)
            self._thread.start()
        return self

    def _watch(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 - the watch must survive
                pass

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=30.0)

    def __enter__(self) -> "Publisher":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

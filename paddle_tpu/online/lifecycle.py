"""Frequency-adaptive sparse-row lifecycle (ROADMAP 4b).

Real CTR vocabularies are heavy-tailed: most ids are seen a handful of
times and their embedding rows are noise. The reference stack handled
this on the parameter server with admit/evict thresholds; here the same
policy runs host-side on the :class:`~paddle_tpu.online.StreamingTrainer`
at batch/task boundaries (the device program is untouched — training
stays bitwise identical for admitted rows):

- **admit-by-touch-count** — a row trains for real only once its id has
  been seen ``admit_touches`` times; until then the policy resets it to
  its deterministic init after every step, so a one-off id never leaves
  noise in the table.
- **TTL-expire** — an id untouched for ``ttl_steps`` optimizer steps is
  evicted: row (and any optimizer accumulators) reset to the
  deterministic init, its touch history dropped. A re-admitted id
  therefore REINITIALIZES DETERMINISTICALLY — byte-equal to its first
  admission (the test pin).

``row_init(row_id)`` is a pure function of (seed, row_id); two trainers
— or one trainer before and after an eviction — produce the identical
row bytes.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional

import numpy as np


class SparseLifecycle:
    """Admit/evict policy over one sparse table.

    table:         scope name of the [V, D] embedding table.
    admit_touches: touches before an id's row starts accumulating
                   training (1 = admit immediately).
    ttl_steps:     evict an id untouched for this many steps.
    row_init:      ``fn(row_id) -> [D] np.ndarray`` deterministic init;
                   default: seeded per-id uniform in [-scale, scale].
    scale, seed:   parameters of the default ``row_init``.
    ids_index:     position of the id column in a training row tuple
                   (ctr rows are ``(ids, dense, label)`` -> 0).
    """

    def __init__(self, table: str, *, admit_touches: int = 2,
                 ttl_steps: int = 200,
                 row_init: Optional[Callable[[int], np.ndarray]] = None,
                 scale: float = 0.1, seed: int = 0, ids_index: int = 0):
        self.table = table
        self.admit_touches = int(admit_touches)
        self.ttl_steps = int(ttl_steps)
        self.scale = float(scale)
        self.seed = int(seed)
        self.ids_index = int(ids_index)
        self._row_init = row_init
        self._dim: Optional[int] = None
        self._dtype = None
        #: id -> [touches, last_step, admitted]
        self._touch: Dict[int, List] = {}
        self.admitted = 0
        self.evicted = 0
        self.suppressed = 0   # pre-admission row resets

    # -- deterministic init --------------------------------------------
    def row_init(self, row_id: int) -> np.ndarray:
        if self._row_init is not None:
            return np.asarray(self._row_init(int(row_id)))
        rng = np.random.default_rng((self.seed, int(row_id)))
        return rng.uniform(-self.scale, self.scale,
                           self._dim).astype(self._dtype or np.float32)

    # -- policy hooks (StreamingTrainer calls these) -------------------
    def _batch_ids(self, batch_rows) -> np.ndarray:
        ids = [np.asarray(row[self.ids_index]).reshape(-1)
               for row in batch_rows]
        return np.unique(np.concatenate(ids)) if ids else np.empty(
            0, np.int64)

    def _accs(self, scope):
        """Optimizer accumulators riding the table (e.g. adagrad's
        ``<table>_moment_acc``) — reset to zero wherever the row is."""
        return [k for k in scope.keys()
                if k.startswith(self.table + "_") and k.endswith("_acc")]

    def _reset_rows(self, scope, ids: List[int]) -> None:
        import jax.numpy as jnp

        w = scope.get(self.table)
        idx = jnp.asarray(np.asarray(ids, np.int32))
        init = jnp.asarray(np.stack([self.row_init(i) for i in ids]))
        scope.set(self.table, w.at[idx].set(init.astype(w.dtype)))
        for acc in self._accs(scope):
            a = scope.get(acc)
            scope.set(acc, a.at[idx].set(jnp.zeros((), a.dtype)))

    def after_batch(self, batch_rows, scope, step: int) -> None:
        """Post-step admit gate: count this batch's touches; rows still
        below the admission threshold are reset to their deterministic
        init (their update this step is discarded)."""
        if self.table not in scope:
            return
        if self._dim is None:
            w = scope.get(self.table)
            self._dim, self._dtype = int(w.shape[-1]), np.dtype(
                str(w.dtype))
        vocab = int(scope.get(self.table).shape[0])
        reset = []
        for i in self._batch_ids(batch_rows):
            i = int(i)
            if i < 0 or i >= vocab:
                continue  # sentinel / padding ids are not rows
            ent = self._touch.get(i)
            if ent is None:
                ent = self._touch[i] = [0, step, False]
            ent[0] += 1
            ent[1] = step
            if not ent[2]:
                if ent[0] >= self.admit_touches:
                    ent[2] = True
                    self.admitted += 1
                    # admission resets ONCE more so training starts from
                    # the deterministic init, not suppressed remnants
                    reset.append(i)
                else:
                    reset.append(i)
                    self.suppressed += 1
        if reset:
            self._reset_rows(scope, reset)

    def on_task_end(self, scope, step: int) -> None:
        """Task-boundary TTL sweep: evict cold ids."""
        if self.table not in scope:
            return
        cold = [i for i, (_, last, _a) in self._touch.items()
                if step - last > self.ttl_steps]
        if not cold:
            return
        for i in cold:
            del self._touch[i]
        self.evicted += len(cold)
        self._reset_rows(scope, cold)

    # -- observability -------------------------------------------------
    def stats(self) -> dict:
        return {"resident": sum(1 for e in self._touch.values()
                                if e[2]),
                "tracked": len(self._touch),
                "admitted": self.admitted, "evicted": self.evicted,
                "suppressed": self.suppressed}

"""paddle_tpu.online — the streaming online-learning plane.

The loop real CTR systems run, assembled from planes earlier PRs built:
train forever on a click-stream (the master's fault-tolerant task queue,
PR 5's checkpoint/resume), on mesh-sharded sparse embeddings (PR 11's
one sharding plane lowering ``vocab_sharded_plan`` through the shard_map
gather/scatter islands), and publish fresh weights into a live serving
fleet with zero downtime (PR 9's ``Fleet.update_weights``), judged by a
freshness SLO on the PR 12 observability plane.

- :class:`StreamingTrainer` — endless-pass training off a master task
  queue; preemption-safe (graceful stop at task boundaries, checkpoint
  resume, deterministic task replay) so a preempted trainer rejoins the
  stream without losing or double-counting tasks. **Elastic mode**
  (``trainer_id=``): N trainers share one queue under the master's
  lease/fencing plane — acks defer until a durable checkpoint generation
  covers them (each generation carries a lineage manifest), zombies are
  fenced out by token, and a preempted trainer rejoins with a fresh
  token by rolling back to the newest durable generation.
- :class:`Publisher` — watches the trainer's checkpoint directory and
  drives rolling ``Fleet.update_weights`` swaps; exports weight-version
  and staleness gauges and the ``freshness`` SLO objective; pins the
  served generation against retention GC and skips (with a counter) a
  generation GC'd between discovery and load.
"""
from .lifecycle import SparseLifecycle
from .publisher import Publisher
from .trainer import StreamingTrainer

__all__ = ["StreamingTrainer", "Publisher", "SparseLifecycle"]

"""StreamingTrainer: endless-pass training off a master task queue.

The trainer side of the online-learning loop. One ``SGD`` step program
(typically Wide&Deep CTR with ``is_sparse`` embeddings) runs forever
over a click-stream served by the fault-tolerant master
(:mod:`paddle_tpu.master`): tasks are pulled, their records batched and
trained, and the task acked (``task_finished``) only after every one of
its batches has been handed to the step loop — so the ack horizon
trails training, never leads it. When a pass drains, ``new_pass()``
recycles the queue and the stream continues (the reference's endless
cluster training, service.go pass recycling).

Preemption contract (pinned by tests/test_online.py):

- **graceful stop** (:meth:`stop`, SIGTERM/SIGINT) latches a flag the
  stream checks at TASK boundaries: the in-flight task finishes
  training and is acked, the pass ends early, ``SGD.train`` writes its
  final checkpoint — every acked task is covered by the checkpoint, no
  task is lost and none is double-counted when a successor resumes.
- **hard crash**: unacked claims time out on the master and re-queue
  (service.go:313); the successor auto-resumes the newest intact
  checkpoint and replays re-served tasks — at-least-once, exactly the
  reference's semantics.

**Elastic mode** (``trainer_id=`` given; pinned by tests/test_elastic.py)
turns at-least-once into exactly-once-effective for N trainers sharing
one master queue, under crash + rejoin + zombie chaos:

- the trainer registers for a lease + monotonic **fencing token**; every
  queue op carries the token, so a zombie (lease expired while it was
  partitioned/paused) can neither ack a task it no longer owns nor — via
  the ``pre_save_fn`` heartbeat veto — publish a checkpoint generation.
- acks are **deferred until the covering generation is durable**: a
  finished task waits in a local pending list, every checkpoint save
  stamps a *lineage manifest* into the generation's meta (writer token,
  master pass, acked horizon, covered-but-unacked task ids), and the
  post-write hook flushes the acks. The ack horizon therefore never runs
  ahead of durable state: a crash after the save but before the ack
  re-serves a task whose updates are already in the checkpoint — which
  the successor detects from the lineage and **skip-acks without
  retraining** (exactly-once effective).
- a fenced trainer (``FencedTokenError``) **rejoins**: fresh token, roll
  the scope back to the newest durable generation (discarding only
  unacked updates — the master requeued those tasks at the queue FRONT,
  so the effective task order is stable), rebuild the covered set from
  the generation's lineage, continue streaming. ``rejoin=False`` exits
  instead (the relay case: a different host takes over).

The checkpoint cadence (``CheckpointConfig.every_n_steps``) is the
weight-generation cadence: every periodic save is a publishable
generation the :class:`~paddle_tpu.online.Publisher` can roll into a
serving fleet. Align it with the task size (``records_per_shard /
batch_size``) and every generation lands at a task boundary — the
configuration under which the crash/rejoin chaos matrix is bitwise.
"""
from __future__ import annotations

import contextlib
import time
from collections import deque
from typing import Callable, Optional, Sequence

from .. import checkpoint as ckpt_mod
from .. import event as evt
from ..master import NO_TASK, PASS_DONE, FencedTokenError, MasterClient
from ..resilience import faults
from ..resilience.faults import SimulatedCrash
from ..resilience.signals import ShutdownFlag, graceful_shutdown


class StreamingTrainer:
    """Drive an ``SGD`` trainer from a master task queue, endlessly.

    sgd:              a built :class:`paddle_tpu.trainer.SGD` (its
                      feed_list names must match the task records'
                      column order).
    master_addr:      (host, port) of a running MasterServer.
    make_task_reader: desc -> record iterator (e.g.
                      ``paddle_tpu.dataset.ctr.task_reader``).
    task_descs:       the dataset; seeded into the master ONLY when its
                      queue is empty — a restarted trainer joining a
                      live master must not reset consumed state.
    batch_size:       records per training step; a task's trailing
                      partial batch trains (short batch) so task ack
                      horizons stay exact.
    checkpoint:       a :class:`~paddle_tpu.resilience.CheckpointConfig`
                      — required for resume and for publishing (its
                      ``every_n_steps`` is the generation cadence).
                      Signal handling moves HERE (task-boundary stop),
                      so the config's ``install_signal_handlers`` is
                      forced off.
    trainer_id:       enables ELASTIC mode: register with the master's
                      lease plane under this id (the "host name" — a
                      preempted host rejoins by re-registering the same
                      id), carry the fencing token on every queue op,
                      defer acks until the covering generation is
                      durable, and stamp lineage manifests onto every
                      generation. Requires ``checkpoint``; forces
                      ``checkpoint.background = False`` (the ack flush
                      must follow the write on the trainer thread).
    lease_s:          lease duration for elastic mode (default 30 s).
    rejoin:           elastic mode: on fencing, re-register + roll back
                      to the newest durable generation and continue
                      (True, default) or stop the run (False — a
                      different host takes over).
    max_steps / max_passes: bound the run (None = endless; ``stop()``
                      or a signal ends it).
    """

    def __init__(self, sgd, master_addr, make_task_reader: Callable,
                 task_descs: Optional[Sequence[str]] = None,
                 batch_size: int = 64, checkpoint=None,
                 max_steps: Optional[int] = None,
                 max_passes: Optional[int] = None,
                 client_retry=None, install_signal_handlers: bool = True,
                 trainer_id: Optional[str] = None,
                 lease_s: float = 30.0, rejoin: bool = True,
                 sparse_lifecycle=None,
                 telemetry_every_s: Optional[float] = None):
        self.sgd = sgd
        #: optional frequency-adaptive row policy (online.lifecycle.
        #: SparseLifecycle): admit gate after every trained batch, TTL
        #: eviction sweep at task boundaries — host-side only, the
        #: device step program is untouched
        self.sparse_lifecycle = sparse_lifecycle
        self.master_addr = tuple(master_addr)
        self.make_task_reader = make_task_reader
        self.task_descs = list(task_descs) if task_descs else None
        self.batch_size = int(batch_size)
        self.checkpoint = checkpoint
        self.trainer_id = trainer_id
        self.lease_s = float(lease_s)
        self._rejoin = bool(rejoin)
        self._elastic = trainer_id is not None
        if self._elastic and checkpoint is None:
            raise ValueError(
                "elastic mode (trainer_id=...) requires a checkpoint "
                "config: deferred acks are only safe against durable "
                "generations")
        if checkpoint is not None:
            # the trainer owns signal handling (task-boundary stop);
            # SGD's own handler would stop mid-task and break the
            # no-double-count contract
            checkpoint.install_signal_handlers = False
        if self._elastic:
            # ack-after-durable needs the write (and the ack flush that
            # follows it) on the trainer thread, in program order
            checkpoint.background = False
            checkpoint.extra_fn = self._lineage
            checkpoint.pre_save_fn = self._pre_save
            checkpoint.on_saved = self._flush_acks
        self.max_steps = max_steps
        self.max_passes = max_passes
        self._client_retry = client_retry
        self._install_signals = bool(install_signal_handlers)
        self._flag = ShutdownFlag()
        self.steps = 0
        self.passes = 0
        self.tasks_finished = 0
        self.tasks_skip_acked = 0   # covered-by-lineage, acked not retrained
        self.rejoins = 0
        self.lease_lost = 0
        self.zombie_acks = 0        # our own acks the master fenced out
        self.last_cost: Optional[float] = None
        self.token: Optional[int] = None
        self._started_at: Optional[float] = None
        self._client: Optional[MasterClient] = None
        self._master_pass = 0
        self._covered: dict = {}        # task_id -> master pass (skip-ack)
        self._finished_pending: list = []   # (tid, epoch): trained, undurable
        self._finishing = None              # (tid, epoch) mid final batch
        self._acked_early: set = set()      # acked by the flush pre-resume
        self._generations = 0               # saves that landed this run
        self._fenced_latch = False
        #: step-telemetry heartbeat cadence (elastic mode): each beat
        #: renews the lease AND ships {step wall, steps, goodput, mfu}
        #: to the master's straggler plane. Default: a third of the
        #: lease so telemetry rides the renewals the lease needs anyway.
        self.telemetry_every_s = (float(telemetry_every_s)
                                  if telemetry_every_s is not None
                                  else max(0.5, self.lease_s / 3.0))
        self.goodput = None                 # set by run()
        self._recent_walls: deque = deque(maxlen=16)
        self._last_end_t: Optional[float] = None
        self._last_stall_s = 0.0
        self._last_telemetry_t = 0.0

    # -- control --------------------------------------------------------
    def stop(self, reason: str = "stop() called") -> None:
        """Latch graceful stop: the stream ends at the next task
        boundary, the final checkpoint covers everything acked."""
        self._flag.set(reason=reason)

    @property
    def stopping(self) -> bool:
        return self._flag.is_set()

    def state(self) -> dict:
        """Operator view: progress counters + the master's queue."""
        out = {"steps": self.steps, "passes": self.passes,
               "tasks_finished": self.tasks_finished,
               "last_cost": self.last_cost,
               "uptime_s": (time.monotonic() - self._started_at
                            if self._started_at else 0.0)}
        if self._elastic:
            out.update({"trainer_id": self.trainer_id, "token": self.token,
                        "rejoins": self.rejoins,
                        "lease_lost": self.lease_lost,
                        "zombie_acks": self.zombie_acks,
                        "tasks_skip_acked": self.tasks_skip_acked})
        if self.goodput is not None:
            out["goodput"] = self.goodput.snapshot()
        try:
            client = MasterClient(self.master_addr,
                                  retry=self._client_retry)
            out["queue"] = client.counts()
            client.close()
        except Exception:  # noqa: BLE001 - state() must not die
            out["queue"] = None
        return out

    # -- elastic plumbing ----------------------------------------------
    def _lineage(self) -> dict:
        """The checkpoint-lineage manifest stamped into every
        generation's ``extra``: who wrote it (fencing token), at which
        master pass, how far the ack horizon reached, and which trained
        tasks the generation covers WITHOUT a master ack yet — the set a
        resuming successor must skip-ack instead of retraining."""
        if not self._elastic:
            return {}
        covered = [tid for tid, _ in self._finished_pending]
        if self._finishing is not None:
            covered.append(self._finishing[0])
        return {"lineage": {
            "writer_token": self.token,
            "trainer_id": self.trainer_id,
            "master_pass": self._master_pass,
            "acked_tasks": self.tasks_finished,
            "covered_unacked": covered,
        }}

    def _pre_save(self) -> bool:
        """Fencing veto: a zombie must not publish a generation. A
        transport failure reaching the master does NOT veto — fencing
        hygiene must not block checkpointing through a master restart."""
        if not self._elastic or self._client is None:
            return True
        try:
            alive = self._client.heartbeat()
        except FencedTokenError:
            alive = False
        except Exception:  # noqa: BLE001 - can't tell; save anyway
            return True
        if not alive:
            self._fenced_latch = True
        return alive

    def _flush_acks(self, step: int, extra: dict) -> None:
        """Post-write hook: the generation at ``step`` is durable, so
        every task it covers may now ack. A rejected ack either means we
        are fenced (latch the rejoin) or the claim timed out server-side
        — then the task is covered by this very generation, and the
        re-serve will be skip-acked."""
        if not self._elastic or self._client is None:
            return
        self._generations += 1
        plan = faults.active_plan()
        if plan is not None and plan.fire("zombie_ack",
                                          self._generations) is not None:
            # injected partition outliving the lease, right before the
            # flush: the acks below must bounce off the fencing check
            self._client._expire_self()
        pending = list(self._finished_pending)
        if self._finishing is not None:
            pending.append(self._finishing)
        acked = set()
        for tid, epoch in pending:
            try:
                ok = self._client.task_finished(tid, epoch)
            except FencedTokenError:
                ok = False
            if ok:
                acked.add(tid)
                self.tasks_finished += 1
                if self._finishing is not None \
                        and tid == self._finishing[0]:
                    self._acked_early.add(tid)
                continue
            alive = False
            try:
                alive = self._client.heartbeat()
            except Exception:  # noqa: BLE001 - fenced or unreachable
                alive = False
            if not alive:
                self.zombie_acks += 1
                self._fenced_latch = True
                break
            # lease alive, claim gone (per-task timeout requeued it):
            # durable in THIS generation -> skip-ack on re-serve
            self._covered[tid] = self._master_pass
        self._finished_pending = [
            p for p in self._finished_pending if p[0] not in acked]

    def _load_covered(self, client: MasterClient) -> None:
        """Rebuild the skip-ack set from the newest durable generation's
        lineage: tasks it covers that the master will re-serve (todo or
        pending at the SAME master pass) ack without retraining."""
        self._covered = {}
        dirname = getattr(self.checkpoint, "dirname", None)
        if not dirname:
            return
        step = ckpt_mod.latest_step(dirname)
        if step is None:
            return
        info = ckpt_mod.generation_info(dirname, step) or {}
        lineage = (info.get("extra") or {}).get("lineage") or {}
        if lineage.get("master_pass") != self._master_pass:
            return  # the pass advanced: everything covered completed
        for tid in lineage.get("covered_unacked", ()):
            if client.task_status(int(tid)) in ("todo", "pending"):
                self._covered[int(tid)] = self._master_pass

    def _skip_if_covered(self, client: MasterClient, tid: int,
                         epoch: int) -> bool:
        if self._covered.get(tid) != self._master_pass:
            return False
        del self._covered[tid]
        if client.task_finished(tid, epoch):
            self.tasks_finished += 1
            self.tasks_skip_acked += 1
        return True

    def _goodput_region(self, bucket: str):
        """The shared meter's region timer, or a no-op when the run is
        uninstrumented."""
        if self.goodput is None:
            return contextlib.nullcontext()
        return self.goodput.measure(bucket)

    def _maybe_telemetry(self, client: MasterClient) -> None:
        """Cadenced heartbeat carrying step telemetry (median recent
        step wall, steps done, goodput fraction, MFU): renews the lease and
        feeds the master's per-trainer straggler digests. Telemetry must
        never kill the stream — transport errors are dropped (the lease
        plane's own renewal paths still run)."""
        if not self._elastic or self.token is None:
            return
        now = time.monotonic()
        if now - self._last_telemetry_t < self.telemetry_every_s:
            return
        self._last_telemetry_t = now
        # median, not mean: a couple of cold-start walls (our own jit
        # compile, or a neighbor's hogging the host) would otherwise sit
        # in the window for its whole depth and read as sustained skew
        walls = sorted(self._recent_walls)
        wall = walls[len(walls) // 2] if walls else None
        if self.goodput is not None:
            payload = self.goodput.telemetry(last_step_wall_s=wall)
        else:
            payload = {}
            if wall is not None:
                payload["step_wall_s"] = round(wall, 6)
        payload["steps"] = self.steps
        try:
            with self._goodput_region("master_wait"):
                client.heartbeat(telemetry=payload)
        except FencedTokenError:
            self._fenced_latch = True
        except Exception:  # noqa: BLE001 - telemetry is best-effort
            pass

    def _handle_fenced(self, client: MasterClient) -> bool:
        """Our token went stale (lease expired / host re-registered).
        Either rejoin — fresh token, scope rolled back to the newest
        durable generation, covered set rebuilt — or end the run for a
        successor host. Returns True when streaming may continue."""
        from .. import profiler, trace

        self._fenced_latch = False
        self.lease_lost += 1
        profiler.global_stat.add_count("trainer/lease_lost", 1)
        if not self._rejoin:
            self.stop("fencing token lost (rejoin disabled)")
            return False
        with trace.span("trainer/rejoin", trainer_id=self.trainer_id), \
                self._goodput_region("recovery_rollback"):
            self.token = client.rejoin()
            dirname = getattr(self.checkpoint, "dirname", None)
            if dirname and ckpt_mod.latest_step(dirname) is not None:
                # discard unacked updates: the master requeued their
                # tasks (front), so we retrain them from durable state
                ckpt_mod.load_checkpoint(dirname, scope=self.sgd.scope,
                                         plan=self.sgd.exe.plan)
            self._finished_pending = []
            self._finishing = None
            self._acked_early = set()
            self._master_pass = int(client.counts().get("pass", 0))
            self._load_covered(client)
        self.rejoins += 1
        profiler.global_stat.add_count("trainer/rejoins", 1)
        return True

    # -- the stream -----------------------------------------------------
    def _maybe_seed(self, client: MasterClient) -> None:
        if not self.task_descs:
            return
        counts = client.counts()
        if (counts["todo"] + counts["pending"] + counts["done"]
                + counts["discarded"]) == 0:
            client.set_dataset(self.task_descs)

    def _budget_left(self) -> bool:
        if self._flag.is_set():
            return False
        if self.max_steps is not None and self.steps >= self.max_steps:
            return False
        if self.max_passes is not None and self.passes >= self.max_passes:
            return False
        return True

    def _task_batches(self, desc: str, tid: int, epoch: int):
        """One task's records as training batches, with one-batch
        lookahead: ``_finishing`` is set just before the FINAL batch is
        yielded, so a checkpoint save firing while the step loop trains
        that batch knows the task is fully covered by the generation."""
        # restart the step-wall clock at the task boundary: the gap to
        # the previous task's last step is queue wait (get_task RPCs,
        # NO_TASK backoff), and letting it into the telemetry digest
        # makes a task-starved trainer look like a straggler
        self._last_end_t = None
        prev = None
        rows = []
        for rec in self.make_task_reader(desc):
            rows.append(rec)
            if len(rows) == self.batch_size:
                if prev is not None:
                    yield prev
                    self._post_batch(prev)
                prev, rows = rows, []
        if rows:  # trailing partial batch still trains
            if prev is not None:
                yield prev
                self._post_batch(prev)
            prev = rows
        if prev is not None:
            if self._elastic:
                self._finishing = (tid, epoch)
            yield prev
            self._post_batch(prev)

    def _post_batch(self, batch) -> None:
        """After a yielded batch RESUMES it has been trained (the step
        loop is synchronous) — count the step and run the sparse-row
        admit gate against the just-updated table."""
        self.steps += 1
        if self.sparse_lifecycle is not None:
            self.sparse_lifecycle.after_batch(batch, self.sgd.scope,
                                              self.steps)

    def _note_task_trained(self, client: MasterClient, tid: int,
                           epoch: int) -> None:
        if not self._elastic:
            client.task_finished(tid, epoch)
            self.tasks_finished += 1
            return
        self._finishing = None
        if tid in self._acked_early:
            # the generation covering this task's final batch already
            # landed AND its flush acked it
            self._acked_early.discard(tid)
            return
        self._finished_pending.append((tid, epoch))

    def _stream_reader(self):
        """The endless batched reader ``SGD.train`` consumes: one
        "pass" from SGD's perspective, internally recycling master
        passes. Tasks ack AFTER their last batch is yielded (the step
        loop trains a yielded batch before pulling the next — sync
        loop) — in elastic mode only once a durable generation covers
        them — and the stop flag is honored only at task boundaries."""

        def reader():
            client = MasterClient(self.master_addr,
                                  retry=self._client_retry)
            self._client = client
            try:
                if self._elastic:
                    self.token = client.register(self.trainer_id,
                                                 lease_s=self.lease_s)
                self._maybe_seed(client)
                if self._elastic:
                    self._master_pass = int(
                        client.counts().get("pass", 0))
                    self._load_covered(client)
                task_no = 0
                while self._budget_left():
                    if self._fenced_latch \
                            and not self._handle_fenced(client):
                        return
                    self._maybe_telemetry(client)
                    plan = faults.active_plan()
                    if plan is not None and plan.fire(
                            "trainer_preempt_rejoin",
                            task_no + 1) is not None:
                        self.stop("fault-plan preemption (rejoin "
                                  "expected)")
                        continue  # the budget check ends the stream
                    try:
                        with self._goodput_region("master_wait"):
                            t = client.get_task()
                    except FencedTokenError:
                        self._fenced_latch = True
                        continue
                    if t == PASS_DONE:
                        self.passes += 1
                        # recycle BEFORE the budget check so a bounded
                        # run always leaves the queue at a fresh pass
                        # boundary for its successor (new_pass is a
                        # no-op while another trainer holds tasks)
                        with self._goodput_region("master_wait"):
                            p = client.new_pass()
                        if p >= 0:
                            self._master_pass = p
                            self._covered = {}
                        continue
                    if t == NO_TASK:
                        # another trainer holds the pending tail
                        with self._goodput_region("master_wait"):
                            time.sleep(0.02)
                        continue
                    tid, desc, epoch = t
                    task_no += 1
                    if plan is not None and plan.fire(
                            "trainer_crash", task_no) is not None:
                        # hard kill with the claim left DANGLING: the
                        # lease plane must fence us and front-requeue it
                        raise SimulatedCrash(
                            f"fault plan: trainer hard crash holding "
                            f"task {tid} (claim #{task_no})")
                    if self._elastic:
                        try:
                            if self._skip_if_covered(client, tid, epoch):
                                continue
                        except FencedTokenError:
                            self._fenced_latch = True
                            continue
                    try:
                        yield from self._task_batches(desc, tid, epoch)
                    except GeneratorExit:
                        # consumer torn down mid-task (trainer crash /
                        # interpreter exit): leave the claim to expire
                        # back into the queue
                        raise
                    except Exception:  # noqa: BLE001 - task retry
                        self._finishing = None
                        try:
                            client.task_failed(tid, epoch)
                        except FencedTokenError:
                            self._fenced_latch = True
                        continue
                    self._note_task_trained(client, tid, epoch)
                    if self.sparse_lifecycle is not None:
                        self.sparse_lifecycle.on_task_end(
                            self.sgd.scope, self.steps)
            finally:
                if not self._elastic:
                    # elastic keeps the client open: SGD's FINAL
                    # checkpoint (written after this generator closes)
                    # must still flush its deferred acks; run() closes it
                    self._client = None
                    client.close()

        # the master tracks consumption; a checkpoint-resumed run must
        # not ALSO skip batches from this stream
        reader.master_backed = True
        return reader

    # -- run ------------------------------------------------------------
    def _flight_state(self) -> dict:
        """Live-state flight-recorder source: progress counters, the
        goodput waterfall and last-N step walls — no network calls, so
        a dump never blocks on a dead master."""
        return {"trainer_id": self.trainer_id, "steps": self.steps,
                "passes": self.passes,
                "tasks_finished": self.tasks_finished,
                "last_cost": self.last_cost,
                "goodput": (self.goodput.snapshot()
                            if self.goodput is not None else None),
                "recent_step_walls_s": [
                    round(w, 6) for w in self._recent_walls]}

    def run(self, event_handler: Optional[Callable] = None,
            run_log=None, **train_kw) -> dict:
        """Train until the budget/stop flag ends the stream; returns the
        final :meth:`state`. Extra kwargs forward to ``SGD.train``
        (e.g. ``mem_budget``, ``plan``). ``goodput`` behaves as in
        :meth:`SGD.train` — the default builds a meter SHARED between
        the step loop and this trainer's master-side accounting, so
        queue idle and rejoin rollback show up as master_wait /
        recovery_rollback instead of inflating data_wait."""
        self._started_at = time.monotonic()
        from ..trace.flight import get_recorder
        from ..trace.goodput import GoodputMeter

        g = train_kw.pop("goodput", None)
        if g is False:
            meter = None
        elif g is None or g is True:
            meter = GoodputMeter()
        else:
            meter = g
        self.goodput = meter
        get_recorder().add_source("streaming_trainer",
                                  self._flight_state)

        def _stalls():
            # already-attributed badput the skew check must NOT see: a
            # synchronous checkpoint write or a fresh compile inside a
            # step interval is bursty I/O, not sustained slowness, and
            # it would flag whoever drew the slowest fsync
            if meter is None:
                return 0.0
            return (meter.bucket_seconds("checkpoint_stall")
                    + meter.bucket_seconds("fresh_compile"))

        def handler(e):
            if isinstance(e, evt.BeginPass):
                self._last_end_t = None
            elif isinstance(e, evt.EndIteration):
                self.last_cost = e.cost
                # resolve-ordered step walls feed the telemetry digest
                now = time.perf_counter()
                stall = _stalls()
                if self._last_end_t is not None:
                    wall = ((now - self._last_end_t)
                            - (stall - self._last_stall_s))
                    if wall > 0:
                        self._recent_walls.append(wall)
                self._last_end_t = now
                self._last_stall_s = stall
            if event_handler is not None:
                event_handler(e)

        ctx = (graceful_shutdown(flag=self._flag)
               if self._install_signals else contextlib.nullcontext())
        try:
            with ctx:
                self.sgd.train(self._stream_reader(), num_passes=1,
                               event_handler=handler, run_log=run_log,
                               checkpoint=self.checkpoint,
                               goodput=meter if meter is not None
                               else False, **train_kw)
        finally:
            client, self._client = self._client, None
            if client is not None:
                client.close()
        return self.state()

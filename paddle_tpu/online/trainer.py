"""StreamingTrainer: endless-pass training off a master task queue.

The trainer side of the online-learning loop. One ``SGD`` step program
(typically Wide&Deep CTR with ``is_sparse`` embeddings) runs forever
over a click-stream served by the fault-tolerant master
(:mod:`paddle_tpu.master`): tasks are pulled, their records batched and
trained, and the task acked (``task_finished``) only after every one of
its batches has been handed to the step loop — so the ack horizon
trails training, never leads it. When a pass drains, ``new_pass()``
recycles the queue and the stream continues (the reference's endless
cluster training, service.go pass recycling).

Preemption contract (pinned by tests/test_online.py):

- **graceful stop** (:meth:`stop`, SIGTERM/SIGINT) latches a flag the
  stream checks at TASK boundaries: the in-flight task finishes
  training and is acked, the pass ends early, ``SGD.train`` writes its
  final checkpoint — every acked task is covered by the checkpoint, no
  task is lost and none is double-counted when a successor resumes.
- **hard crash**: unacked claims time out on the master and re-queue
  (service.go:313); the successor auto-resumes the newest intact
  checkpoint and replays re-served tasks — at-least-once, exactly the
  reference's semantics.

The checkpoint cadence (``CheckpointConfig.every_n_steps``) is the
weight-generation cadence: every periodic save is a publishable
generation the :class:`~paddle_tpu.online.Publisher` can roll into a
serving fleet.
"""
from __future__ import annotations

import time
from typing import Callable, Optional, Sequence

from .. import event as evt
from ..master import NO_TASK, PASS_DONE, MasterClient
from ..resilience.signals import ShutdownFlag, graceful_shutdown


class StreamingTrainer:
    """Drive an ``SGD`` trainer from a master task queue, endlessly.

    sgd:              a built :class:`paddle_tpu.trainer.SGD` (its
                      feed_list names must match the task records'
                      column order).
    master_addr:      (host, port) of a running MasterServer.
    make_task_reader: desc -> record iterator (e.g.
                      ``paddle_tpu.dataset.ctr.task_reader``).
    task_descs:       the dataset; seeded into the master ONLY when its
                      queue is empty — a restarted trainer joining a
                      live master must not reset consumed state.
    batch_size:       records per training step; a task's trailing
                      partial batch trains (short batch) so task ack
                      horizons stay exact.
    checkpoint:       a :class:`~paddle_tpu.resilience.CheckpointConfig`
                      — required for resume and for publishing (its
                      ``every_n_steps`` is the generation cadence).
                      Signal handling moves HERE (task-boundary stop),
                      so the config's ``install_signal_handlers`` is
                      forced off.
    max_steps / max_passes: bound the run (None = endless; ``stop()``
                      or a signal ends it).
    """

    def __init__(self, sgd, master_addr, make_task_reader: Callable,
                 task_descs: Optional[Sequence[str]] = None,
                 batch_size: int = 64, checkpoint=None,
                 max_steps: Optional[int] = None,
                 max_passes: Optional[int] = None,
                 client_retry=None, install_signal_handlers: bool = True):
        self.sgd = sgd
        self.master_addr = tuple(master_addr)
        self.make_task_reader = make_task_reader
        self.task_descs = list(task_descs) if task_descs else None
        self.batch_size = int(batch_size)
        self.checkpoint = checkpoint
        if checkpoint is not None:
            # the trainer owns signal handling (task-boundary stop);
            # SGD's own handler would stop mid-task and break the
            # no-double-count contract
            checkpoint.install_signal_handlers = False
        self.max_steps = max_steps
        self.max_passes = max_passes
        self._client_retry = client_retry
        self._install_signals = bool(install_signal_handlers)
        self._flag = ShutdownFlag()
        self.steps = 0
        self.passes = 0
        self.tasks_finished = 0
        self.last_cost: Optional[float] = None
        self._started_at: Optional[float] = None

    # -- control --------------------------------------------------------
    def stop(self, reason: str = "stop() called") -> None:
        """Latch graceful stop: the stream ends at the next task
        boundary, the final checkpoint covers everything acked."""
        self._flag.set(reason=reason)

    @property
    def stopping(self) -> bool:
        return self._flag.is_set()

    def state(self) -> dict:
        """Operator view: progress counters + the master's queue."""
        out = {"steps": self.steps, "passes": self.passes,
               "tasks_finished": self.tasks_finished,
               "last_cost": self.last_cost,
               "uptime_s": (time.monotonic() - self._started_at
                            if self._started_at else 0.0)}
        try:
            client = MasterClient(self.master_addr,
                                  retry=self._client_retry)
            out["queue"] = client.counts()
            client.close()
        except Exception:  # noqa: BLE001 - state() must not die
            out["queue"] = None
        return out

    # -- the stream -----------------------------------------------------
    def _maybe_seed(self, client: MasterClient) -> None:
        if not self.task_descs:
            return
        counts = client.counts()
        if (counts["todo"] + counts["pending"] + counts["done"]
                + counts["discarded"]) == 0:
            client.set_dataset(self.task_descs)

    def _budget_left(self) -> bool:
        if self._flag.is_set():
            return False
        if self.max_steps is not None and self.steps >= self.max_steps:
            return False
        if self.max_passes is not None and self.passes >= self.max_passes:
            return False
        return True

    def _stream_reader(self):
        """The endless batched reader ``SGD.train`` consumes: one
        "pass" from SGD's perspective, internally recycling master
        passes. Tasks ack AFTER their last batch is yielded (the step
        loop trains a yielded batch before pulling the next — sync
        loop), and the stop flag is honored only at task boundaries."""

        def reader():
            client = MasterClient(self.master_addr,
                                  retry=self._client_retry)
            try:
                self._maybe_seed(client)
                while self._budget_left():
                    t = client.get_task()
                    if t == PASS_DONE:
                        self.passes += 1
                        # recycle BEFORE the budget check so a bounded
                        # run always leaves the queue at a fresh pass
                        # boundary for its successor (new_pass is a
                        # no-op while another trainer holds tasks)
                        client.new_pass()
                        continue
                    if t == NO_TASK:
                        # another trainer holds the pending tail
                        time.sleep(0.02)
                        continue
                    tid, desc, epoch = t
                    try:
                        rows = []
                        for rec in self.make_task_reader(desc):
                            rows.append(rec)
                            if len(rows) == self.batch_size:
                                yield rows
                                self.steps += 1
                                rows = []
                        if rows:  # trailing partial batch still trains
                            yield rows
                            self.steps += 1
                    except GeneratorExit:
                        # consumer torn down mid-task (trainer crash /
                        # interpreter exit): leave the claim to expire
                        # back into the queue
                        raise
                    except Exception:  # noqa: BLE001 - task retry
                        client.task_failed(tid, epoch)
                        continue
                    client.task_finished(tid, epoch)
                    self.tasks_finished += 1
            finally:
                client.close()

        # the master tracks consumption; a checkpoint-resumed run must
        # not ALSO skip batches from this stream
        reader.master_backed = True
        return reader

    # -- run ------------------------------------------------------------
    def run(self, event_handler: Optional[Callable] = None,
            run_log=None, **train_kw) -> dict:
        """Train until the budget/stop flag ends the stream; returns the
        final :meth:`state`. Extra kwargs forward to ``SGD.train``
        (e.g. ``mem_budget``, ``plan``)."""
        self._started_at = time.monotonic()

        def handler(e):
            if isinstance(e, evt.EndIteration):
                self.last_cost = e.cost
            if event_handler is not None:
                event_handler(e)

        import contextlib

        ctx = (graceful_shutdown(flag=self._flag)
               if self._install_signals else contextlib.nullcontext())
        with ctx:
            self.sgd.train(self._stream_reader(), num_passes=1,
                           event_handler=handler, run_log=run_log,
                           checkpoint=self.checkpoint, **train_kw)
        return self.state()

"""Optimizer classes: build backward + update ops into the program.

Mirrors /root/reference/python/paddle/v2/fluid/optimizer.py: ``minimize``
appends the backward pass then one update op per parameter, creating
accumulator state (velocity/moments/pows) as persistable vars initialised in
the startup program. Because the executor compiles the whole block, the
entire step — forward, backward, all N parameter updates — is one fused XLA
computation with donated parameter buffers.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from .core.backward import append_backward
from .core.program import Program, Variable, default_startup_program
from .layers.layer_helper import LayerHelper
from .regularizer import append_regularization_ops


def _sparse_grad_params(block) -> set:
    """Parameters whose gradient arrives as a SelectedRows: the weights
    of ``is_sparse=True`` lookup_table ops (lookup_table_op.cc:59 emits
    the row-sparse grad). Optimizers with a row-granular update rule
    emit their ``sparse_*`` op for these, so the step never materializes
    a [V, D] gradient."""
    names = set()
    for op in block.ops:
        if op.type == "lookup_table" and op.attrs.get("is_sparse", False):
            names.update(op.inputs.get("W", ()))
    return names


class Optimizer:
    op_type: str = None

    def __init__(self, learning_rate: float = 0.001, global_step=None,
                 regularization=None):
        self.learning_rate = learning_rate
        self.global_step = global_step
        self.regularization = regularization
        self._lr_var: Optional[Variable] = None
        self._accumulators: Dict[str, Dict[str, Variable]] = {}
        self._sparse_params: set = set()

    # -- learning rate -----------------------------------------------------
    def _create_lr_var(self, program: Program, startup: Program) -> Variable:
        if self._lr_var is not None:
            return self._lr_var
        if hasattr(self.learning_rate, "name"):
            # a program-computed LR Variable (learning_rate_decay schedule)
            self._lr_var = self.learning_rate
            return self._lr_var
        name = program.unique_name("learning_rate")
        block = program.global_block
        v = block.create_var(name=name, shape=[1], dtype="float32",
                             persistable=True, stop_gradient=True)
        sb = startup.global_block
        sv = sb.create_var(name=name, shape=[1], dtype="float32", persistable=True)
        sb.append_op("fill_constant", outputs={"Out": [name]},
                     attrs={"shape": [1], "dtype": "float32",
                            "value": float(self.learning_rate)})
        self._lr_var = v
        return v

    # -- accumulators ------------------------------------------------------
    def _add_accumulator(self, name: str, param: Variable, startup: Program,
                         fill_value: float = 0.0, shape=None,
                         dtype="float32") -> Variable:
        shape = list(shape if shape is not None else param.shape)
        var_name = f"{param.name}_{name}_acc"
        block = param.block.program.global_block
        v = block.create_var(name=var_name, shape=shape, dtype=dtype,
                             persistable=True, stop_gradient=True)
        sb = startup.global_block
        sb.create_var(name=var_name, shape=shape, dtype=dtype, persistable=True)
        sb.append_op("fill_constant", outputs={"Out": [var_name]},
                     attrs={"shape": shape, "dtype": dtype,
                            "value": float(fill_value)})
        self._accumulators.setdefault(name, {})[param.name] = v
        return v

    def _get_accumulator(self, name, param) -> Variable:
        return self._accumulators[name][param.name]

    # -- per-algorithm hooks ----------------------------------------------
    def _create_accumulators(self, startup, params: List[Variable]):
        pass

    def _append_optimize_op(self, block, param_and_grad, lr_var):
        raise NotImplementedError

    # -- public API --------------------------------------------------------
    def minimize(self, loss: Variable, startup_program: Optional[Program] = None,
                 parameter_list=None, no_grad_set=None,
                 accumulate_steps: int = 1
                 ) -> List[Tuple[Variable, Variable]]:
        """Append backward + update ops for ``loss``.

        ``accumulate_steps`` > 1 turns on in-graph gradient accumulation:
        each run adds the micro-batch gradient into a persistent buffer
        and the optimizer (including its momentum/Adam state and the
        LR-schedule step) applies only every k-th run, on the MEAN of the
        k gradients — so k micro-batches reproduce one large-batch step
        exactly. The accumulation buffers are named ``*_gradsum_acc`` and
        inherit a parameter's sharding-plan rules like any optimizer
        accumulator (e.g. ZeRO shards them over dp)."""
        from .clip import append_gradient_clip_ops

        startup = startup_program or default_startup_program()
        params_grads = append_backward(loss, parameter_list, no_grad_set)
        block = loss.block
        self._sparse_params = _sparse_grad_params(block)
        lr_var = self._create_lr_var(block.program, startup)
        if accumulate_steps and int(accumulate_steps) > 1:
            # clip/reg must see the accumulated MEAN gradient (clipping a
            # micro-batch then averaging != clipping the mean) — they are
            # appended inside the accumulation plumbing instead
            self._create_accumulators(startup,
                                      [p for p, _ in params_grads])
            self._minimize_accumulated(block, startup, params_grads,
                                       lr_var, int(accumulate_steps))
            self._append_updater_hooks(block, startup,
                                       [p for p, _ in params_grads])
            return params_grads
        # clip BEFORE regularization — fluid's order
        # (reference optimizer.py runs append_gradient_clip_ops first, then
        # append_regularization_ops)
        params_grads = append_gradient_clip_ops(params_grads)
        params_grads = append_regularization_ops(params_grads,
                                                 self.regularization)
        self._create_accumulators(startup, [p for p, _ in params_grads])
        for pg in params_grads:
            self._append_optimize_op(block, pg, lr_var)
        if self.global_step is not None:
            block.append_op("increment",
                            inputs={"X": [self.global_step.name]},
                            outputs={"Out": [self.global_step.name]},
                            attrs={"step": 1.0})
        self._append_updater_hooks(block, startup,
                                   [p for p, _ in params_grads])
        return params_grads

    def _minimize_accumulated(self, block, startup, params_grads, lr_var,
                              k: int):
        """Gradient accumulation: buffer += grad each run; every k-th run
        clip + regularization + the optimizer op apply on the MEAN of the
        k gradients, and every state write (param, velocity/moments/
        beta-pows, step counters) lands only through a gate — off-step
        runs leave all state bit-identical.

        Counter gating preserves dtypes (LR-schedule counters are int32
        by design); schedules driven by the shared ``lr_global_step``
        counter advance once per apply. A USER-supplied ``global_step``
        passed directly into a decay fn cannot be discovered here and
        would still tick per micro-batch — pass it as the optimizer's
        ``global_step`` instead."""
        from . import layers as L
        from .clip import append_gradient_clip_ops

        kw = dict(main_program=block.program, startup_program=startup)
        counter = L.create_global_var(
            shape=[1], value=0.0, dtype="float32",
            name=block.program.unique_name("grad_acc_step"), **kw)
        block.append_op("increment", inputs={"X": [counter.name]},
                        outputs={"Out": [counter.name]},
                        attrs={"step": 1.0})
        k_c = L.fill_constant(shape=[1], value=float(k), dtype="float32",
                              **kw)
        gate = L.cast(L.equal(counter, k_c, **kw), "float32", **kw)
        inv_gate = L.scale(gate, scale=-1.0, bias=1.0, **kw)
        # counter resets on apply (no mod op needed)
        block.append_op("elementwise_mul",
                        inputs={"X": [counter.name],
                                "Y": [inv_gate.name]},
                        outputs={"Out": [counter.name]}, attrs={})

        def gated_advance(name, dtype_name):
            """counter += gate, in the counter's OWN dtype (int32 LR
            counters must stay int32 — f32 freezes at 2^24)."""
            g_typed = L.cast(gate, dtype_name, **kw)                 if dtype_name != "float32" else gate
            block.append_op("elementwise_add",
                            inputs={"X": [name], "Y": [g_typed.name]},
                            outputs={"Out": [name]}, attrs={})

        # LR schedules carry their own per-run counters whose increment
        # ops were appended at schedule-build time; subtract the
        # increment back on off-steps so decay advances once per APPLY
        shared = getattr(block.program, "_lr_step_counter", None)
        lr_counters = {n for op in block.ops if op.type == "increment"
                       for n in op.inputs.get("X", [])
                       if "lr_global_step" in n}
        if shared is not None:
            lr_counters.add(shared.name)
        for name in sorted(lr_counters):
            var = block.vars[name]
            ig_typed = L.cast(inv_gate, var.dtype.name, **kw)                 if var.dtype.name != "float32" else inv_gate
            block.append_op("elementwise_sub",
                            inputs={"X": [name], "Y": [ig_typed.name]},
                            outputs={"Out": [name]}, attrs={})

        # pass 1: accumulate and form every mean
        means = []
        accs = []
        for p, g in params_grads:
            acc = self._add_accumulator("gradsum", p, startup)
            accs.append(acc)
            block.append_op("elementwise_add",
                            inputs={"X": [acc.name], "Y": [g.name]},
                            outputs={"Out": [acc.name]}, attrs={})
            means.append(L.scale(acc, scale=1.0 / k, **kw))
        # clip + regularize the MEANS (global-norm clip needs them all)
        pg_mean = append_gradient_clip_ops(
            [(p, m) for (p, _), m in zip(params_grads, means)])
        pg_mean = append_regularization_ops(pg_mean, self.regularization)
        # pass 2: gated optimize per param
        for (p, mean), acc in zip(pg_mean, accs):
            states = [p] + [vars_[p.name]
                            for name, vars_ in self._accumulators.items()
                            if name != "gradsum" and p.name in vars_]
            olds = [L.assign(s, **kw) for s in states]
            self._append_optimize_op(block, (p, mean), lr_var)
            for s, old in zip(states, olds):
                # s = old + gate * (s - old): the off-step run keeps old
                delta = L.elementwise_sub(s, old, **kw)
                gated = L.elementwise_mul(delta, gate, **kw)
                block.append_op("elementwise_add",
                                inputs={"X": [old.name],
                                        "Y": [gated.name]},
                                outputs={"Out": [s.name]}, attrs={})
            block.append_op("elementwise_mul",
                            inputs={"X": [acc.name],
                                    "Y": [inv_gate.name]},
                            outputs={"Out": [acc.name]}, attrs={})
        if self.global_step is not None:
            gated_advance(self.global_step.name,
                          self.global_step.dtype.name)

    def _append_updater_hooks(self, block, startup, params):
        """ParameterUpdaterHook plane (reference ParameterUpdaterHook.cpp):
        for params carrying a StaticPruningHook, build the fixed mask from
        the initialized weights in the startup program (pruning them
        there too, matching the hook's init()) and re-apply the mask after
        every update in the main program."""
        from .param_attr import StaticPruningHook

        for p in params:
            for hook in getattr(p, "update_hooks", ()) or ():
                if not isinstance(hook, StaticPruningHook):
                    raise TypeError(f"unsupported updater hook {hook!r}")
                mask_name = p.name + "@PRUNE_MASK"
                sb = startup.global_block
                sb.create_var(name=mask_name, shape=p.shape, dtype=p.dtype,
                              persistable=True)
                sb.append_op(
                    "static_prune_mask", inputs={"Param": [p.name]},
                    outputs={"Mask": [mask_name]},
                    attrs={"sparsity_ratio": hook.sparsity_ratio})
                sb.append_op("elementwise_mul",
                             inputs={"X": [p.name], "Y": [mask_name]},
                             outputs={"Out": [p.name]}, attrs={})
                block.create_var(name=mask_name, shape=p.shape,
                                 dtype=p.dtype, persistable=True,
                                 stop_gradient=True)
                block.append_op("elementwise_mul",
                                inputs={"X": [p.name], "Y": [mask_name]},
                                outputs={"Out": [p.name]}, attrs={})


class SGDOptimizer(Optimizer):
    op_type = "sgd"

    def _append_optimize_op(self, block, pg, lr_var):
        p, g = pg
        op_type = "sparse_sgd" if p.name in self._sparse_params else "sgd"
        block.append_op(
            op_type,
            inputs={"Param": [p.name], "Grad": [g.name],
                    "LearningRate": [lr_var.name]},
            outputs={"ParamOut": [p.name]})


class MomentumOptimizer(Optimizer):
    op_type = "momentum"

    def __init__(self, learning_rate, momentum=0.9, use_nesterov=False, **kw):
        super().__init__(learning_rate, **kw)
        self.momentum = momentum
        self.use_nesterov = use_nesterov

    def _create_accumulators(self, startup, params):
        for p in params:
            self._add_accumulator("velocity", p, startup)

    def _append_optimize_op(self, block, pg, lr_var):
        p, g = pg
        v = self._get_accumulator("velocity", p)
        block.append_op(
            "momentum",
            inputs={"Param": [p.name], "Grad": [g.name], "Velocity": [v.name],
                    "LearningRate": [lr_var.name]},
            outputs={"ParamOut": [p.name], "VelocityOut": [v.name]},
            attrs={"mu": self.momentum, "use_nesterov": self.use_nesterov})


class AdamOptimizer(Optimizer):
    op_type = "adam"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kw):
        super().__init__(learning_rate, **kw)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.weight_decay = 0.0  # AdamWOptimizer overrides

    def _create_accumulators(self, startup, params):
        for p in params:
            self._add_accumulator("moment1", p, startup)
            self._add_accumulator("moment2", p, startup)
            self._add_accumulator("beta1_pow", p, startup, self.beta1, shape=[1])
            self._add_accumulator("beta2_pow", p, startup, self.beta2, shape=[1])

    def _append_optimize_op(self, block, pg, lr_var):
        p, g = pg
        block.append_op(
            "adam",
            inputs={"Param": [p.name], "Grad": [g.name],
                    "LearningRate": [lr_var.name],
                    "Moment1": [self._get_accumulator("moment1", p).name],
                    "Moment2": [self._get_accumulator("moment2", p).name],
                    "Beta1Pow": [self._get_accumulator("beta1_pow", p).name],
                    "Beta2Pow": [self._get_accumulator("beta2_pow", p).name]},
            outputs={"ParamOut": [p.name],
                     "Moment1Out": [self._get_accumulator("moment1", p).name],
                     "Moment2Out": [self._get_accumulator("moment2", p).name],
                     "Beta1PowOut": [self._get_accumulator("beta1_pow", p).name],
                     "Beta2PowOut": [self._get_accumulator("beta2_pow", p).name]},
            attrs={"beta1": self.beta1, "beta2": self.beta2,
                   "epsilon": self.epsilon,
                   "weight_decay": self.weight_decay})


class AdamWOptimizer(AdamOptimizer):
    """Adam with DECOUPLED weight decay (beyond-reference: the modern LM
    training default). Decay applies directly to the parameter
    (p -= lr*wd*p), outside the moment estimates — unlike
    ``regularization=L2Decay(...)``, which adds wd*p into the gradient
    and therefore into the Adam moments."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, weight_decay=0.01, **kw):
        super().__init__(learning_rate, beta1=beta1, beta2=beta2,
                         epsilon=epsilon, **kw)
        self.weight_decay = weight_decay


class AdamaxOptimizer(Optimizer):
    op_type = "adamax"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kw):
        super().__init__(learning_rate, **kw)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, startup, params):
        for p in params:
            self._add_accumulator("moment", p, startup)
            self._add_accumulator("inf_norm", p, startup)
            self._add_accumulator("beta1_pow", p, startup, self.beta1, shape=[1])

    def _append_optimize_op(self, block, pg, lr_var):
        p, g = pg
        block.append_op(
            "adamax",
            inputs={"Param": [p.name], "Grad": [g.name],
                    "LearningRate": [lr_var.name],
                    "Moment": [self._get_accumulator("moment", p).name],
                    "InfNorm": [self._get_accumulator("inf_norm", p).name],
                    "Beta1Pow": [self._get_accumulator("beta1_pow", p).name]},
            outputs={"ParamOut": [p.name],
                     "MomentOut": [self._get_accumulator("moment", p).name],
                     "InfNormOut": [self._get_accumulator("inf_norm", p).name],
                     "Beta1PowOut": [self._get_accumulator("beta1_pow", p).name]},
            attrs={"beta1": self.beta1, "beta2": self.beta2,
                   "epsilon": self.epsilon})


class AdagradOptimizer(Optimizer):
    op_type = "adagrad"

    def __init__(self, learning_rate, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self.epsilon = epsilon

    def _create_accumulators(self, startup, params):
        for p in params:
            self._add_accumulator("moment", p, startup)

    def _append_optimize_op(self, block, pg, lr_var):
        p, g = pg
        m = self._get_accumulator("moment", p)
        op_type = ("sparse_adagrad" if p.name in self._sparse_params
                   else "adagrad")
        block.append_op(
            op_type,
            inputs={"Param": [p.name], "Grad": [g.name], "Moment": [m.name],
                    "LearningRate": [lr_var.name]},
            outputs={"ParamOut": [p.name], "MomentOut": [m.name]},
            attrs={"epsilon": self.epsilon})


class DecayedAdagradOptimizer(Optimizer):
    op_type = "decayed_adagrad"

    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self.decay, self.epsilon = decay, epsilon

    def _create_accumulators(self, startup, params):
        for p in params:
            self._add_accumulator("moment", p, startup)

    def _append_optimize_op(self, block, pg, lr_var):
        p, g = pg
        m = self._get_accumulator("moment", p)
        block.append_op(
            "decayed_adagrad",
            inputs={"Param": [p.name], "Grad": [g.name], "Moment": [m.name],
                    "LearningRate": [lr_var.name]},
            outputs={"ParamOut": [p.name], "MomentOut": [m.name]},
            attrs={"decay": self.decay, "epsilon": self.epsilon})


class AdadeltaOptimizer(Optimizer):
    op_type = "adadelta"

    def __init__(self, learning_rate=1.0, rho=0.95, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self.rho, self.epsilon = rho, epsilon

    def _create_accumulators(self, startup, params):
        for p in params:
            self._add_accumulator("avg_sq_grad", p, startup)
            self._add_accumulator("avg_sq_update", p, startup)

    def _append_optimize_op(self, block, pg, lr_var):
        p, g = pg
        asg = self._get_accumulator("avg_sq_grad", p)
        asu = self._get_accumulator("avg_sq_update", p)
        block.append_op(
            "adadelta",
            inputs={"Param": [p.name], "Grad": [g.name],
                    "AvgSquaredGrad": [asg.name], "AvgSquaredUpdate": [asu.name]},
            outputs={"ParamOut": [p.name], "AvgSquaredGradOut": [asg.name],
                     "AvgSquaredUpdateOut": [asu.name]},
            attrs={"rho": self.rho, "epsilon": self.epsilon})


class RMSPropOptimizer(Optimizer):
    op_type = "rmsprop"

    def __init__(self, learning_rate, decay=0.9, momentum=0.0, epsilon=1e-10,
                 **kw):
        super().__init__(learning_rate, **kw)
        self.decay, self.momentum, self.epsilon = decay, momentum, epsilon

    def _create_accumulators(self, startup, params):
        for p in params:
            self._add_accumulator("mean_square", p, startup)
            self._add_accumulator("moment", p, startup)

    def _append_optimize_op(self, block, pg, lr_var):
        p, g = pg
        ms = self._get_accumulator("mean_square", p)
        m = self._get_accumulator("moment", p)
        block.append_op(
            "rmsprop",
            inputs={"Param": [p.name], "Grad": [g.name],
                    "MeanSquare": [ms.name], "Moment": [m.name],
                    "LearningRate": [lr_var.name]},
            outputs={"ParamOut": [p.name], "MeanSquareOut": [ms.name],
                     "MomentOut": [m.name]},
            attrs={"decay": self.decay, "momentum": self.momentum,
                   "epsilon": self.epsilon})


class FtrlOptimizer(Optimizer):
    op_type = "ftrl"

    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5, **kw):
        super().__init__(learning_rate, **kw)
        self.l1, self.l2, self.lr_power = l1, l2, lr_power

    def _create_accumulators(self, startup, params):
        for p in params:
            self._add_accumulator("squared_acc", p, startup)
            self._add_accumulator("linear_acc", p, startup)

    def _append_optimize_op(self, block, pg, lr_var):
        p, g = pg
        sq = self._get_accumulator("squared_acc", p)
        lin = self._get_accumulator("linear_acc", p)
        block.append_op(
            "ftrl",
            inputs={"Param": [p.name], "Grad": [g.name],
                    "SquaredAccumulator": [sq.name],
                    "LinearAccumulator": [lin.name],
                    "LearningRate": [lr_var.name]},
            outputs={"ParamOut": [p.name], "SquaredAccumOut": [sq.name],
                     "LinearAccumOut": [lin.name]},
            attrs={"l1": self.l1, "l2": self.l2, "lr_power": self.lr_power})


# fluid aliases
SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adam = AdamOptimizer
AdamW = AdamWOptimizer
Adamax = AdamaxOptimizer
Adagrad = AdagradOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Ftrl = FtrlOptimizer


class ModelAverage:
    """Windowed parameter averaging for evaluation (reference
    parameter/AverageOptimizer.h; fluid optimizer.py ModelAverage).

    Build AFTER Optimizer.minimize: appends a model_average_update op per
    trainable parameter so the running window accumulates inside the
    training step. ``apply(scope)`` (a context manager) swaps the averaged
    values into the scope for eval and restores the live parameters on
    exit — the PARAMETER_APPLY/restore dance of the reference.

    Two-buffer rotation (sum_1 live, sum_2 last full window) instead of
    the reference's three: the apply-time average spans one to two
    windows of history. ``min_average_window`` gates apply: with fewer
    accumulated steps the live parameters are kept.
    """

    def __init__(self, average_window_rate: float = 0.15,
                 min_average_window: int = 100,
                 max_average_window: int = 10000,
                 main_program: Optional[Program] = None,
                 startup_program: Optional[Program] = None):
        from .core.program import default_main_program

        del average_window_rate  # window is bounded explicitly, as in fluid
        self.min_average_window = int(min_average_window)
        self.max_average_window = int(max_average_window)
        main = main_program or default_main_program()
        startup = startup_program or default_startup_program()
        from .initializer import ConstantInitializer

        block = main.global_block
        self._slots: List[Tuple[str, Dict[str, str]]] = []
        for p in block.all_parameters():
            if not p.trainable:
                continue
            names = {}
            for suffix, shape in (("sum_1", p.shape), ("sum_2", p.shape),
                                  ("num_1", [1]), ("num_2", [1])):
                name = f"{p.name}@MA_{suffix}"
                names[suffix] = name
                block.create_var(name=name, shape=shape, dtype="float32",
                                 persistable=True, stop_gradient=True)
                sv = startup.global_block.create_var(
                    name=name, shape=shape, dtype="float32",
                    persistable=True)
                ConstantInitializer(0.0)(sv, startup.global_block)
            block.append_op(
                "model_average_update",
                inputs={"Param": [p.name], "Sum1": [names["sum_1"]],
                        "Sum2": [names["sum_2"]], "Num1": [names["num_1"]],
                        "Num2": [names["num_2"]]},
                outputs={"Sum1Out": [names["sum_1"]],
                         "Sum2Out": [names["sum_2"]],
                         "Num1Out": [names["num_1"]],
                         "Num2Out": [names["num_2"]]},
                attrs={"max_average_window": self.max_average_window})
            self._slots.append((p.name, names))

    def apply(self, scope=None):
        """Context manager: scope holds averaged params inside, live
        params are restored on exit."""
        import contextlib

        from .core.scope import global_scope

        scope = scope or global_scope()

        @contextlib.contextmanager
        def _ctx():
            backup = {}
            for pname, names in self._slots:
                s1 = np.asarray(scope.get_numpy(names["sum_1"]))
                s2 = np.asarray(scope.get_numpy(names["sum_2"]))
                n = (float(np.asarray(scope.get_numpy(names["num_1"]))[0])
                     + float(np.asarray(scope.get_numpy(names["num_2"]))[0]))
                if n <= 0 or n < self.min_average_window:
                    continue
                backup[pname] = np.asarray(scope.get_numpy(pname))
                avg = ((s1 + s2) / n).astype(backup[pname].dtype)
                scope.set(pname, avg)
            try:
                yield self
            finally:
                for pname, val in backup.items():
                    scope.set(pname, val)

        return _ctx()

"""Learning-rate decay schedules, computed in-graph from a step counter.

Parity surface for the reference's LR scheduling on both engines:
- legacy: /root/reference/paddle/parameter/LearningRateScheduler.cpp (poly,
  caffe_poly, exp, discrete_exp, linear, manual policies selected by
  OptimizationConfig.learning_rate_schedule).
- fluid: optimizer's ``global_step`` counter
  (/root/reference/python/paddle/v2/fluid/optimizer.py) — the decay-function
  API below follows the shape fluid grew for it.

Each function returns a [1] float32 Variable recomputed by the training
program every step from a persistable step counter, so the whole schedule
lives inside the compiled step (no recompiles, no host round-trips). Pass
the result as ``Optimizer(learning_rate=...)``.
"""
from __future__ import annotations

from .layers import tensor as tensor_layers
from .layers.layer_helper import LayerHelper

__all__ = [
    "step_counter", "exponential_decay", "natural_exp_decay",
    "inverse_time_decay", "polynomial_decay", "piecewise_decay",
    "noam_decay", "linear_lr_warmup",
]


def step_counter(main_program=None, startup_program=None, begin=0):
    """A persistable int32 [1] counter incremented once per program run
    (fluid's autoincreased global step). Integer by design: a float32
    counter silently freezes at 2^24 steps; int32 is exact to 2^31.

    One shared counter per main program: schedules created without an
    explicit ``global_step`` reuse it (and its single increment op), so
    stacking e.g. warmup over a decay adds no duplicate counters."""
    helper = LayerHelper("lr_global_step", main_program=main_program,
                         startup_program=startup_program)
    main = helper.main_program
    cached = getattr(main, "_lr_step_counter", None)
    if cached is not None:
        if int(begin) != cached._begin:
            raise ValueError(
                f"this program's shared LR step counter already starts at "
                f"{cached._begin}; cannot re-create it with begin="
                f"{int(begin)}. Pass the counter explicitly as global_step "
                f"to use a different origin.")
        return cached
    counter = tensor_layers.create_global_var(
        shape=[1], value=int(begin), dtype="int32",
        name=main.unique_name("lr_global_step"),
        main_program=main, startup_program=helper.startup_program)
    counter._begin = int(begin)
    helper.block.append_op("increment", inputs={"X": [counter.name]},
                           outputs={"Out": [counter.name]},
                           attrs={"step": 1})
    main._lr_step_counter = counter
    return counter


def _schedule(policy, attrs, global_step, main_program, startup_program):
    helper = LayerHelper("lr_schedule", main_program=main_program,
                         startup_program=startup_program)
    if global_step is None:
        global_step = step_counter(main_program=helper.main_program,
                                   startup_program=helper.startup_program)
    return helper.simple_op("lr_schedule", {"GlobalStep": [global_step]},
                            dict(attrs, policy=policy))


def exponential_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False, global_step=None,
                      main_program=None, startup_program=None):
    """lr * decay_rate^(step/decay_steps) (ExpLRS)."""
    return _schedule("exponential",
                     {"learning_rate": float(learning_rate),
                      "decay_steps": int(decay_steps),
                      "decay_rate": float(decay_rate),
                      "staircase": bool(staircase)},
                     global_step, main_program, startup_program)


def natural_exp_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False, global_step=None,
                      main_program=None, startup_program=None):
    """lr * exp(-decay_rate * step/decay_steps)."""
    return _schedule("natural_exp",
                     {"learning_rate": float(learning_rate),
                      "decay_steps": int(decay_steps),
                      "decay_rate": float(decay_rate),
                      "staircase": bool(staircase)},
                     global_step, main_program, startup_program)


def inverse_time_decay(learning_rate, decay_steps, decay_rate,
                       staircase=False, global_step=None,
                       main_program=None, startup_program=None):
    """lr / (1 + decay_rate * step/decay_steps) (LinearLRS analogue)."""
    return _schedule("inverse_time",
                     {"learning_rate": float(learning_rate),
                      "decay_steps": int(decay_steps),
                      "decay_rate": float(decay_rate),
                      "staircase": bool(staircase)},
                     global_step, main_program, startup_program)


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=1e-4,
                     power=1.0, cycle=False, global_step=None,
                     main_program=None, startup_program=None):
    """(lr - end)*(1 - step/decay_steps)^power + end (PolyLRS)."""
    return _schedule("polynomial",
                     {"learning_rate": float(learning_rate),
                      "decay_steps": int(decay_steps),
                      "end_learning_rate": float(end_learning_rate),
                      "power": float(power), "cycle": bool(cycle)},
                     global_step, main_program, startup_program)


def piecewise_decay(boundaries, values, global_step=None,
                    main_program=None, startup_program=None):
    """Step-wise constant LR: values[i] while step < boundaries[i]
    (DiscreteExpLRS / ManualLRS policies)."""
    if len(values) != len(boundaries) + 1:
        raise ValueError("piecewise_decay needs len(values) == "
                         "len(boundaries) + 1")
    return _schedule("piecewise",
                     {"boundaries": [float(b) for b in boundaries],
                      "values": [float(v) for v in values]},
                     global_step, main_program, startup_program)


def cosine_decay(learning_rate, decay_steps, alpha=0.0, global_step=None,
                 main_program=None, startup_program=None):
    """Cosine annealing (beyond-reference; the modern LM default):
    lr * ((1-alpha) * 0.5*(1+cos(pi*step/decay_steps)) + alpha),
    clamped at ``alpha*lr`` past ``decay_steps``. Compose with
    ``linear_lr_warmup`` for the standard warmup+cosine recipe."""
    return _schedule("cosine",
                     {"learning_rate": float(learning_rate),
                      "decay_steps": int(decay_steps),
                      "alpha": float(alpha)},
                     global_step, main_program, startup_program)


def noam_decay(d_model, warmup_steps, global_step=None,
               main_program=None, startup_program=None):
    """The transformer schedule: d_model^-0.5 * min(s^-0.5, s*warmup^-1.5)."""
    return _schedule("noam",
                     {"d_model": float(d_model),
                      "warmup_steps": int(warmup_steps)},
                     global_step, main_program, startup_program)


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr,
                     global_step=None, main_program=None,
                     startup_program=None):
    """Ramp start_lr -> end_lr over warmup_steps, then follow
    ``learning_rate`` (a Variable from a decay above, or a float)."""
    helper = LayerHelper("lr_warmup", main_program=main_program,
                         startup_program=startup_program)
    if global_step is None:
        global_step = step_counter(main_program=helper.main_program,
                                   startup_program=helper.startup_program)
    if not hasattr(learning_rate, "name"):  # plain float
        learning_rate = tensor_layers.fill_constant(
            shape=[1], dtype="float32", value=float(learning_rate),
            main_program=helper.main_program,
            startup_program=helper.startup_program)
    return helper.simple_op(
        "lr_warmup",
        {"LearningRate": [learning_rate], "GlobalStep": [global_step]},
        {"warmup_steps": int(warmup_steps), "start_lr": float(start_lr),
         "end_lr": float(end_lr)})

"""Composite network helpers (fluid nets.py parity:
/root/reference/python/paddle/v2/fluid/nets.py — simple_img_conv_pool,
img_conv_group, sequence_conv_pool, glu)."""
from __future__ import annotations

from . import layers


def simple_img_conv_pool(input, num_filters, filter_size, pool_size,
                         pool_stride, act, pool_type="max", data_format="NCHW",
                         param_attr=None, main_program=None,
                         startup_program=None):
    conv_out = layers.conv2d(
        input=input, num_filters=num_filters, filter_size=filter_size,
        param_attr=param_attr, act=act, data_format=data_format,
        main_program=main_program, startup_program=startup_program)
    return layers.pool2d(
        input=conv_out, pool_size=pool_size, pool_type=pool_type,
        pool_stride=pool_stride, data_format=data_format,
        main_program=main_program, startup_program=startup_program)


def img_conv_group(input, conv_num_filter, conv_filter_size=3, conv_act=None,
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0.0,
                   pool_size=2, pool_stride=2, pool_type="max",
                   data_format="NCHW", main_program=None,
                   startup_program=None):
    """VGG-style conv block: N convs (+BN/dropout) then one pool."""
    tmp = input
    if isinstance(conv_num_filter, int):
        conv_num_filter = [conv_num_filter]
    n = len(conv_num_filter)

    def per_conv(x, default):
        return x if isinstance(x, (list, tuple)) else [x] * n

    sizes = per_conv(conv_filter_size, 3)
    with_bn = per_conv(conv_with_batchnorm, False)
    drop = per_conv(conv_batchnorm_drop_rate, 0.0)
    for i in range(n):
        local_act = conv_act if not with_bn[i] else None
        tmp = layers.conv2d(
            input=tmp, num_filters=conv_num_filter[i], filter_size=sizes[i],
            padding=(sizes[i] - 1) // 2, act=local_act, data_format=data_format,
            main_program=main_program, startup_program=startup_program)
        if with_bn[i]:
            tmp = layers.batch_norm(input=tmp, act=conv_act,
                                    data_layout=data_format,
                                    main_program=main_program,
                                    startup_program=startup_program)
            if drop[i] > 0:
                tmp = layers.dropout(x=tmp, dropout_prob=drop[i],
                                     main_program=main_program,
                                     startup_program=startup_program)
    return layers.pool2d(input=tmp, pool_size=pool_size, pool_type=pool_type,
                         pool_stride=pool_stride, data_format=data_format,
                         main_program=main_program,
                         startup_program=startup_program)


def glu(input, dim=-1, main_program=None, startup_program=None):
    a, b = layers.split(input, 2, dim=dim, main_program=main_program,
                        startup_program=startup_program)
    gate = layers.sigmoid(b, main_program=main_program,
                          startup_program=startup_program)
    return layers.elementwise_mul(a, gate, main_program=main_program,
                                  startup_program=startup_program)

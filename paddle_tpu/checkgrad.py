"""Gradient checking as a first-class job.

Mirrors the reference's ``--job=checkgrad`` trainer mode
(/root/reference/paddle/trainer/TrainerMain.cpp:54, Trainer.cpp checkGradient)
and the OpTest numeric-gradient harness
(/root/reference/python/paddle/v2/fluid/tests/op_test.py:80
get_numeric_gradient): compare the program-built backward pass against
central finite differences for every trainable parameter.

TPU dtype policy (SURVEY.md §7 'matching the test harness'): the check
forces 'highest' MXU precision (true f32 contractions) for the duration —
the default bf16-multiply fast path has ~1e-2 noise that would swamp a
1e-4 finite-difference comparison.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from .core.backward import append_backward
from .core.program import GRAD_SUFFIX, grad_var_name
from .ops import common as ops_common


def check_gradients(program, feed: Dict[str, np.ndarray], loss,
                    scope=None, params: Optional[List[str]] = None,
                    delta: float = 1e-3, rtol: float = 1e-2,
                    atol: float = 1e-4, max_elements: int = 64,
                    startup_program=None,
                    executor=None) -> List[Tuple[str, float]]:
    """Run the checkgrad job. Returns [(param_name, max_rel_error)] and
    raises AssertionError on the first parameter exceeding tolerance.

    ``program`` must already contain the loss; backward ops are appended to
    a CLONE so the caller's program is untouched. At most ``max_elements``
    randomly-chosen elements per parameter are perturbed (the reference
    sweeps all; sampling keeps TPU round-trips bounded).
    """
    import paddle_tpu as pt

    scope = scope if scope is not None else pt.global_scope()
    exe = executor or pt.Executor(pt.TPUPlace())

    prog = program.clone()
    block = prog.global_block
    # Truncate everything after the op producing the loss: a program built
    # via Optimizer.minimize carries backward + update ops, and running
    # those during a finite-difference probe would mutate the very weights
    # being measured (the reference's checkgrad job likewise runs forward
    # only, TrainerInternal checkGradient path).
    loss_idx = max(i for i, op in enumerate(block.ops)
                   if loss.name in op.output_names())
    del block.ops[loss_idx + 1:]
    # drop stale @GRAD vars inherited from the original minimize() backward;
    # otherwise append_backward renames its fresh grads to avoid them
    for name in [n for n in block.vars if GRAD_SUFFIX in n]:
        del block.vars[name]
    with pt.program_guard(prog, startup_program or pt.Program()):
        loss_var = block.var(loss.name)
        param_grads = append_backward(loss_var)
    if params is None:
        params = [p.name for p, _ in param_grads]
    grad_names = {p.name: g.name for p, g in param_grads}

    old_precision = ops_common._MXU_PRECISION
    ops_common.set_mxu_precision("highest")
    try:
        fetch = [loss.name] + [grad_names[p] for p in params]
        outs = exe.run(prog, feed=feed, fetch_list=fetch, scope=scope)
        analytic = dict(zip(params, outs[1:]))

        def loss_at() -> float:
            (lo,) = exe.run(prog, feed=feed, fetch_list=[loss.name],
                            scope=scope)
            return float(np.asarray(lo).sum())

        results = []
        rng = np.random.RandomState(0)
        for pname in params:
            base = np.array(scope.get(pname), copy=True)
            flat = base.reshape(-1)
            n = flat.size
            idxs = (np.arange(n) if n <= max_elements
                    else rng.choice(n, size=max_elements, replace=False))
            worst = 0.0
            a = np.asarray(analytic[pname]).reshape(-1)
            for i in idxs:
                for sign, store in ((+1, "hi"), (-1, "lo")):
                    pert = flat.copy()
                    pert[i] += sign * delta
                    scope.set(pname, pert.reshape(base.shape))
                    if sign > 0:
                        hi = loss_at()
                    else:
                        lo = loss_at()
                numeric = (hi - lo) / (2 * delta)
                err = abs(numeric - a[i]) / max(
                    max(abs(numeric), abs(a[i])), atol / rtol)
                worst = max(worst, err)
                if err > rtol:
                    scope.set(pname, base)
                    raise AssertionError(
                        f"gradient check FAILED for {pname}[{i}]: "
                        f"numeric={numeric:.6g} analytic={a[i]:.6g} "
                        f"rel_err={err:.3g} > {rtol}")
            scope.set(pname, base)
            results.append((pname, worst))
        return results
    finally:
        ops_common._MXU_PRECISION = old_precision

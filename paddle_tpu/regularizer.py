"""Weight-decay regularizers (fluid regularizer.py parity).

Appends decay ops onto each parameter's gradient before the optimizer op, as
the reference does (/root/reference/python/paddle/v2/fluid/regularizer.py).
"""
from __future__ import annotations


class WeightDecayRegularizer:
    def append_decay(self, param, grad, block):
        raise NotImplementedError


class L2DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff: float = 0.0):
        self.coeff = regularization_coeff

    def append_decay(self, param, grad, block):
        program = block.program
        decay = program.unique_name(param.name + "@L2DECAY")
        block.create_var(name=decay, shape=param.shape, dtype=param.dtype,
                         stop_gradient=True)
        block.append_op("scale", inputs={"X": [param.name]},
                        outputs={"Out": [decay]}, attrs={"scale": self.coeff})
        out = program.unique_name(grad.name + "@REG")
        block.create_var(name=out, shape=param.shape, dtype=param.dtype,
                         stop_gradient=True)
        block.append_op("sum", inputs={"X": [grad.name, decay]},
                        outputs={"Out": [out]})
        return block.var(out)


class L1DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff: float = 0.0):
        self.coeff = regularization_coeff

    def append_decay(self, param, grad, block):
        program = block.program
        decay = program.unique_name(param.name + "@L1DECAY")
        block.create_var(name=decay, shape=param.shape, dtype=param.dtype,
                         stop_gradient=True)
        block.append_op("l1_decay_sign", inputs={"X": [param.name]},
                        outputs={"Out": [decay]}, attrs={"coeff": self.coeff})
        out = program.unique_name(grad.name + "@REG")
        block.create_var(name=out, shape=param.shape, dtype=param.dtype,
                         stop_gradient=True)
        block.append_op("sum", inputs={"X": [grad.name, decay]},
                        outputs={"Out": [out]})
        return block.var(out)


def append_regularization_ops(params_grads, regularization=None):
    """Apply per-param (or global) regularizers to gradients."""
    result = []
    for param, grad in params_grads:
        reg = getattr(param, "regularizer", None) or regularization
        if reg is None:
            result.append((param, grad))
            continue
        new_grad = reg.append_decay(param, grad, param.block.program.global_block)
        result.append((param, new_grad))
    return result


L1Decay = L1DecayRegularizer
L2Decay = L2DecayRegularizer

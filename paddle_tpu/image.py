"""Image preprocessing utilities (reference python/paddle/v2/image.py API).

The reference implements these over cv2; here they are numpy-first (PIL
only decodes files/bytes), because on TPU systems the input pipeline runs
on plain host CPUs and the arrays feed straight into NHWC device batches.
Images are HWC uint8 (or HW for grayscale) throughout, matching the
reference's convention; ``to_chw`` converts at the very end for callers
that want the reference's CHW layout.

API parity (image.py): load_image / load_image_bytes, resize_short,
center_crop, random_crop, left_right_flip, to_chw, simple_transform,
load_and_transform, batch_images_from_tar.
"""
from __future__ import annotations

import os
import pickle
import tarfile
from typing import Optional, Sequence

import numpy as np

__all__ = [
    "load_image", "load_image_bytes", "resize_short", "center_crop",
    "random_crop", "left_right_flip", "to_chw", "simple_transform",
    "load_and_transform", "batch_images_from_tar",
]


def _to_array(pil_img, is_color: bool) -> np.ndarray:
    pil_img = pil_img.convert("RGB" if is_color else "L")
    return np.asarray(pil_img)


def load_image(file: str, is_color: bool = True) -> np.ndarray:
    """Decode an image file to HWC (color) / HW (gray) uint8."""
    from PIL import Image

    with Image.open(file) as im:
        return _to_array(im, is_color)


def load_image_bytes(data: bytes, is_color: bool = True) -> np.ndarray:
    """Decode an in-memory encoded image (the tar/record path)."""
    import io

    from PIL import Image

    with Image.open(io.BytesIO(data)) as im:
        return _to_array(im, is_color)


def resize_short(im: np.ndarray, size: int) -> np.ndarray:
    """Scale so the SHORTER edge equals ``size``, keeping aspect ratio."""
    from PIL import Image

    h, w = im.shape[:2]
    if h <= w:
        nh, nw = size, max(1, int(round(w * size / h)))
    else:
        nh, nw = max(1, int(round(h * size / w))), size
    if (nh, nw) == (h, w):
        return im
    pil = Image.fromarray(im)
    return np.asarray(pil.resize((nw, nh), Image.BILINEAR))


def to_chw(im: np.ndarray, order: Sequence[int] = (2, 0, 1)) -> np.ndarray:
    """HWC -> CHW (or any axis order); grayscale HW gains a 1-channel."""
    if im.ndim == 2:
        im = im[:, :, None]
    return im.transpose(order)


def _crop(im: np.ndarray, h0: int, w0: int, size: int) -> np.ndarray:
    return im[h0:h0 + size, w0:w0 + size]


def center_crop(im: np.ndarray, size: int,
                is_color: bool = True) -> np.ndarray:
    h, w = im.shape[:2]
    if h < size or w < size:
        raise ValueError(f"image {h}x{w} smaller than crop {size}")
    return _crop(im, (h - size) // 2, (w - size) // 2, size)


def random_crop(im: np.ndarray, size: int, is_color: bool = True,
                rng: Optional[np.random.RandomState] = None) -> np.ndarray:
    h, w = im.shape[:2]
    if h < size or w < size:
        raise ValueError(f"image {h}x{w} smaller than crop {size}")
    rng = rng or np.random
    return _crop(im, rng.randint(0, h - size + 1),
                 rng.randint(0, w - size + 1), size)


def left_right_flip(im: np.ndarray) -> np.ndarray:
    return im[:, ::-1]


def simple_transform(im: np.ndarray, resize_size: int, crop_size: int,
                     is_train: bool, is_color: bool = True,
                     mean: Optional[np.ndarray] = None,
                     rng: Optional[np.random.RandomState] = None
                     ) -> np.ndarray:
    """The standard train/eval pipeline: resize-short, then random crop +
    coin-flip mirror (train) or center crop (eval), CHW float32, optional
    mean subtraction (scalar, per-channel [C], or full [C,H,W])."""
    rng = rng or np.random
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size, is_color, rng=rng)
        if rng.randint(2):
            im = left_right_flip(im)
    else:
        im = center_crop(im, crop_size, is_color)
    im = to_chw(im).astype(np.float32)
    if mean is not None:
        mean = np.asarray(mean, np.float32)
        if mean.ndim == 1:
            mean = mean.reshape(-1, 1, 1)
        im = im - mean
    return im


def load_and_transform(filename: str, resize_size: int, crop_size: int,
                       is_train: bool, is_color: bool = True,
                       mean: Optional[np.ndarray] = None) -> np.ndarray:
    return simple_transform(load_image(filename, is_color), resize_size,
                            crop_size, is_train, is_color, mean)


def batch_images_from_tar(data_file: str, dataset_name: str,
                          img2label: dict, num_per_batch: int = 1024
                          ) -> str:
    """Pre-batch a tar of images into pickled numpy batches
    (reference image.py batch_images_from_tar): each output batch file
    holds {'data': [encoded bytes], 'label': [int]}; returns the path of
    the batch directory, with a 'batch_names.txt' manifest."""
    out_dir = data_file + "_" + dataset_name + "_batch"
    os.makedirs(out_dir, exist_ok=True)
    names, data, labels, batch_id = [], [], [], 0

    def _flush():
        nonlocal data, labels, batch_id
        if not data:
            return
        path = os.path.join(out_dir, f"batch_{batch_id:05d}")
        with open(path, "wb") as f:
            pickle.dump({"data": data, "label": labels}, f,
                        protocol=pickle.HIGHEST_PROTOCOL)
        names.append(os.path.basename(path))
        data, labels = [], []
        batch_id += 1

    with tarfile.open(data_file) as tf:
        for member in tf.getmembers():
            if not member.isfile() or member.name not in img2label:
                continue
            data.append(tf.extractfile(member).read())
            labels.append(int(img2label[member.name]))
            if len(data) >= num_per_batch:
                _flush()
    _flush()
    with open(os.path.join(out_dir, "batch_names.txt"), "w") as f:
        f.write("\n".join(names))
    return out_dir

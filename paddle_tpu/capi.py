"""Inference machines: the native C ABI binding and its serving reroute.

``InferenceMachine`` mirrors the reference's paddle/capi usage pattern
(/root/reference/paddle/capi/capi.h, examples/model_inference/dense):
create a machine from a saved model, feed inputs, forward, read outputs —
no Python framework (and no JAX) in the serving process. C/C++
applications link the compiled shared library directly.

``EngineInferenceMachine`` is the same surface reroute through
:mod:`paddle_tpu.serving`: the forward runs through a pre-warmed,
bucket-padded InferenceEngine instead of the per-call native machine, so
repeated ``run``/``generate`` calls hit the compile cache and share the
engine's metrics plane. ``inference_machine()`` picks whichever backend
the environment supports — existing capi callers get the serving path for
free where no C++ toolchain exists.
"""
from __future__ import annotations

import ctypes
from typing import Dict, List

import numpy as np

from .native.build import load_library


def _autoregressive_generate(run, feed_names, prompt, max_new_tokens: int,
                             seq_len: int, input_name: str = None,
                             fetch_index: int = 0, pad_id: int = 0,
                             temperature: float = 0.0, top_k: int = 0,
                             seed: int = 0) -> np.ndarray:
    """The host-side decode loop shared by every one-shot machine
    (native C and serving-engine backed): the saved per-layer LM has a
    STATIC [*, seq_len] input (its position table is sliced at build
    time), so each step feeds the ids buffer padded to ``seq_len`` and
    re-runs the full forward — causal attention makes positions past the
    cursor irrelevant. O(n * full-forward); deployments wanting the O(n)
    KV-cache path serve a stacked LM through
    serving.GenerationEngine instead. Greedy by default; ``temperature``
    > 0 samples (optionally ``top_k`` truncated) on the host from the
    machine-computed distribution. prompt: [b, p] ints ->
    [b, p + max_new_tokens]."""
    prompt = np.asarray(prompt, dtype=np.int64)
    b, p = prompt.shape
    if p < 1:
        raise ValueError("generate needs at least one prompt token "
                         "(position -1 would wrap to the pad tail)")
    if p + max_new_tokens > seq_len:
        raise ValueError(
            f"prompt ({p}) + max_new_tokens ({max_new_tokens}) "
            f"exceeds the model's static seq_len ({seq_len})")
    if temperature < 0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    name = input_name or feed_names[0]
    rng = np.random.RandomState(seed)
    ids = np.full((b, seq_len), pad_id, np.int64)
    ids[:, :p] = prompt
    for cur in range(p, p + max_new_tokens):
        row = run({name: ids})[fetch_index][:, cur - 1, :]
        if temperature > 0:
            # Sampling treats the fetched row as PROBABILITIES (the
            # docstring contract). Negative entries mean the fetch is
            # logits — log() would silently invert their ranking, so
            # fail loudly; NaN/Inf means a broken model.
            if not np.isfinite(row).all():
                raise ValueError(
                    "generate(temperature>0): model output contains "
                    "NaN/Inf — cannot sample from it")
            if (row < 0).any():
                raise ValueError(
                    "generate(temperature>0): model output has "
                    "negative entries — sampling needs softmax "
                    "probabilities, not logits (fetch the softmax "
                    "output, or use temperature=0 greedy decode "
                    "which accepts logits)")
            z = np.log(np.maximum(row.astype(np.float64), 1e-30))
            z /= temperature
            if top_k:
                if not 0 < int(top_k) <= row.shape[-1]:
                    raise ValueError(
                        f"top_k must be in (0, vocab={row.shape[-1]}],"
                        f" got {top_k}")
                kth = np.sort(z, axis=-1)[:, -int(top_k)][:, None]
                z = np.where(z >= kth, z, -np.inf)
            z -= z.max(-1, keepdims=True)
            pr = np.exp(z)
            pr /= pr.sum(-1, keepdims=True)
            ids[:, cur] = [rng.choice(pr.shape[-1], p=pr[i])
                           for i in range(b)]
        else:
            ids[:, cur] = row.argmax(-1)
    return ids[:, :p + max_new_tokens]


def _lib():
    lib = load_library("capi")
    if lib is None:
        raise RuntimeError("no C++ toolchain available for the capi "
                           "inference machine")
    lib.pdtpu_load.restype = ctypes.c_void_p
    lib.pdtpu_load.argtypes = [ctypes.c_char_p]
    lib.pdtpu_last_error.restype = ctypes.c_char_p
    lib.pdtpu_free.argtypes = [ctypes.c_void_p]
    lib.pdtpu_num_feeds.argtypes = [ctypes.c_void_p]
    lib.pdtpu_feed_name.restype = ctypes.c_char_p
    lib.pdtpu_feed_name.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.pdtpu_num_fetches.argtypes = [ctypes.c_void_p]
    lib.pdtpu_fetch_name.restype = ctypes.c_char_p
    lib.pdtpu_fetch_name.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.pdtpu_set_input.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int]
    lib.pdtpu_run.argtypes = [ctypes.c_void_p]
    lib.pdtpu_output_rank.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.pdtpu_output_shape.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64)]
    lib.pdtpu_output_numel.restype = ctypes.c_int64
    lib.pdtpu_output_numel.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.pdtpu_output_data.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_float), ctypes.c_int64]
    return lib


class InferenceMachine:
    """C-side forward-only machine over a save_inference_model directory
    (the paddle_gradient_machine analogue)."""

    def __init__(self, model_dir: str):
        self._lib = _lib()
        self._h = self._lib.pdtpu_load(model_dir.encode())
        if not self._h:
            raise RuntimeError(
                "pdtpu_load failed: "
                + self._lib.pdtpu_last_error().decode())

    @property
    def feed_names(self) -> List[str]:
        n = self._lib.pdtpu_num_feeds(self._h)
        return [self._lib.pdtpu_feed_name(self._h, i).decode()
                for i in range(n)]

    @property
    def fetch_names(self) -> List[str]:
        n = self._lib.pdtpu_num_fetches(self._h)
        return [self._lib.pdtpu_fetch_name(self._h, i).decode()
                for i in range(n)]

    def run(self, feed: Dict[str, np.ndarray]) -> List[np.ndarray]:
        for name, arr in feed.items():
            arr = np.ascontiguousarray(arr, dtype=np.float32)
            shape = (ctypes.c_int64 * arr.ndim)(*arr.shape)
            rc = self._lib.pdtpu_set_input(
                self._h, name.encode(),
                arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                shape, arr.ndim)
            if rc != 0:
                raise RuntimeError(self._lib.pdtpu_last_error().decode())
        if self._lib.pdtpu_run(self._h) != 0:
            raise RuntimeError(self._lib.pdtpu_last_error().decode())
        outs = []
        for name in self.fetch_names:
            rank = self._lib.pdtpu_output_rank(self._h, name.encode())
            if rank < 0:
                raise RuntimeError(self._lib.pdtpu_last_error().decode())
            shape = (ctypes.c_int64 * max(rank, 1))()
            self._lib.pdtpu_output_shape(self._h, name.encode(), shape)
            numel = self._lib.pdtpu_output_numel(self._h, name.encode())
            buf = np.empty(int(numel), np.float32)
            rc = self._lib.pdtpu_output_data(
                self._h, name.encode(),
                buf.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), numel)
            if rc != 0:
                raise RuntimeError(self._lib.pdtpu_last_error().decode())
            outs.append(buf.reshape(tuple(shape[:rank])))
        return outs

    def generate(self, prompt, max_new_tokens: int, seq_len: int,
                 input_name: str = None, fetch_index: int = 0,
                 pad_id: int = 0, temperature: float = 0.0,
                 top_k: int = 0, seed: int = 0) -> np.ndarray:
        """Autoregressive decode through the C machine — greedy by
        default; ``temperature`` > 0 samples (optionally ``top_k``
        truncated) on the host from the C-computed distribution.

        The decode loop itself is the module-level
        ``_autoregressive_generate`` — shared with the serving-engine
        machine, so both backends keep identical semantics. The fetched
        target must be the [*, seq_len, vocab] next-token distribution
        (softmax probs when sampling; logits also work for greedy).
        prompt: [b, p] ints -> [b, p + max_new_tokens]."""
        return _autoregressive_generate(
            self.run, self.feed_names, prompt, max_new_tokens, seq_len,
            input_name=input_name, fetch_index=fetch_index, pad_id=pad_id,
            temperature=temperature, top_k=top_k, seed=seed)

    def close(self):
        if getattr(self, "_h", None):
            self._lib.pdtpu_free(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class EngineInferenceMachine:
    """InferenceMachine surface rerouted through the serving engine.

    Same run/generate contract as the native machine, but the forward
    goes through a pre-warmed serving.InferenceEngine: batches pad to
    warm buckets (zero compiles on the serving path after ``warmup()``),
    and repeated generate() steps reuse the one compiled shape. Drop-in
    for environments without a C++ toolchain — and the batching/metrics
    story the bare ctypes binding never had."""

    def __init__(self, model_dir: str, **engine_kw):
        from .serving import InferenceEngine

        self._engine = InferenceEngine(model_dir, **engine_kw)
        self._engine.warmup()

    @property
    def engine(self):
        return self._engine

    @property
    def feed_names(self) -> List[str]:
        return list(self._engine.feed_names)

    @property
    def fetch_names(self) -> List[str]:
        return list(self._engine.fetch_names)

    def run(self, feed: Dict[str, np.ndarray]) -> List[np.ndarray]:
        return self._engine.run(feed)

    def generate(self, prompt, max_new_tokens: int, seq_len: int,
                 input_name: str = None, fetch_index: int = 0,
                 pad_id: int = 0, temperature: float = 0.0,
                 top_k: int = 0, seed: int = 0) -> np.ndarray:
        """Autoregressive decode through the engine — the shared host
        loop over a static [*, seq_len] saved LM (see
        ``_autoregressive_generate``). Every step feeds the same padded
        shape, so after the first step the whole decode is compile-free."""
        return _autoregressive_generate(
            self.run, self.feed_names, prompt, max_new_tokens, seq_len,
            input_name=input_name, fetch_index=fetch_index, pad_id=pad_id,
            temperature=temperature, top_k=top_k, seed=seed)

    def close(self):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def inference_machine(model_dir: str, backend: str = "auto", **engine_kw):
    """Open a saved inference model with the best available machine.

    backend: 'native' (the C ABI binding; raises without a toolchain),
    'engine' (the Python serving engine), or 'auto' — native when a
    C++ toolchain is present, otherwise the serving engine."""
    if backend == "native":
        return InferenceMachine(model_dir)
    if backend == "engine":
        return EngineInferenceMachine(model_dir, **engine_kw)
    if backend != "auto":
        raise ValueError(f"unknown backend {backend!r} "
                         "(expected 'native', 'engine', or 'auto')")
    try:
        return InferenceMachine(model_dir)
    except RuntimeError:
        return EngineInferenceMachine(model_dir, **engine_kw)

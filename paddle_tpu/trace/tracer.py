"""Hierarchical span tracer — the Dapper-style backbone of the telemetry
plane.

A ``Span`` is one named, timed region with attributes, a unique id, and a
link to its parent; spans from one logical operation (a serving request, an
executor run) share a ``trace_id``. Nesting is tracked per thread (a
thread-local span stack), so ``with span("a"): with span("b"): ...``
records ``b`` as a child of ``a`` with no plumbing. Cross-thread
operations (a request admitted on an HTTP thread, executed on the dispatch
thread) use *detached* spans: ``start_span(..., detached=True)`` returns a
handle that never touches any stack and is ended explicitly — children on
other threads link to it by passing ``parent=``.

Completed spans land in a bounded ring buffer (oldest fall off — tracing a
long-lived server never grows without bound) and are drained by the
exporters in :mod:`paddle_tpu.trace.export`. Sampling is counter-based and
deterministic (no RNG): with ``sample_rate=r``, an accumulator keeps
exactly the fraction ``r`` of ROOT spans, and an unsampled root suppresses
its entire subtree — children cost one thread-local check, nothing is
recorded.

Levels (the ``--trace_level`` flag / ``trace.enable(level=...)``):
  0  tracing off — every ``span()`` is a near-free no-op;
  1  span tracing: executor compile/run, serving request/queue/execute,
     trainer iterations;
  2  per-op debug: ``Executor.run`` additionally switches to the
     interpret-mode path (op-by-op host dispatch with per-op spans,
     output stats, and located NaN/Inf diagnosis).

Cross-process context: ``Tracer.inject()`` renders the current (or a
given) span as a W3C ``traceparent`` header value and
``Tracer.extract()`` parses one back into a :class:`SpanContext` usable
as ``parent=`` — the seam the serving fleet uses to carry ONE trace id
across router attempt -> HTTP hop -> remote replica. Trace ids are
128-bit random (globally unique without coordination, never a
per-process counter) and span ids carry a per-process salt, so journals
from N processes stitch without collisions
(``tools/trace_summary.py --distributed``).
"""
from __future__ import annotations

import contextlib
import itertools
import secrets
import threading
import time
from collections import deque
from typing import Dict, Iterator, List, Optional

# Default ring-buffer capacity: generous for a debug session, bounded for
# a long-lived traced server (at ~200 B/span this is ~3 MB).
DEFAULT_CAPACITY = 16384


def _new_trace_id() -> int:
    """Globally-unique 128-bit trace id (W3C forbids all-zero)."""
    return secrets.randbits(128) | 1


class SpanContext:
    """A span reference without the span — what ``extract()`` returns
    for a parent living in ANOTHER process. Carries exactly the two
    fields ``start_span(parent=...)``/``record(parent=...)`` read, so a
    remote parent and a local one are interchangeable."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: int, span_id: int):
        self.trace_id = int(trace_id)
        self.span_id = int(span_id)

    def __repr__(self):
        return (f"SpanContext(trace={self.trace_id:032x}, "
                f"span={self.span_id:016x})")


class Span:
    """One named, timed region. ``start``/``end`` are seconds on the
    tracer's monotonic clock (``perf_counter`` relative to the tracer's
    epoch); ``attrs`` is a plain JSON-safe dict."""

    __slots__ = ("name", "span_id", "parent_id", "trace_id", "start",
                 "end", "attrs", "thread", "_tracer")

    def __init__(self, name: str, span_id: int, parent_id: Optional[int],
                 trace_id: int, start: float, thread: int, tracer):
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.trace_id = trace_id
        self.start = start
        self.end: Optional[float] = None
        self.attrs: Dict[str, object] = {}
        self.thread = thread
        self._tracer = tracer

    # -- attribute plane ---------------------------------------------------
    def set_attr(self, key: str, value) -> "Span":
        self.attrs[key] = value
        return self

    def set_attrs(self, **kv) -> "Span":
        self.attrs.update(kv)
        return self

    @property
    def duration(self) -> Optional[float]:
        return None if self.end is None else self.end - self.start

    def finish(self, **attrs) -> None:
        """End a detached span (context-managed spans end themselves)."""
        if attrs:
            self.attrs.update(attrs)
        if self._tracer is not None:
            self._tracer._end_span(self)

    def to_dict(self) -> dict:
        return {"name": self.name, "span_id": self.span_id,
                "parent_id": self.parent_id, "trace_id": self.trace_id,
                "start_s": self.start, "end_s": self.end,
                "duration_s": self.duration, "thread": self.thread,
                "attrs": dict(self.attrs)}

    def __repr__(self):
        dur = f"{self.duration * 1e3:.3f}ms" if self.end is not None \
            else "open"
        return (f"Span({self.name!r}, id={self.span_id}, "
                f"parent={self.parent_id}, {dur})")


class Tracer:
    """Span factory + bounded completed-span buffer.

    One process-global instance (``get_tracer()``) serves the whole
    stack; tests construct private ones. All public methods are safe to
    call with tracing disabled — they degrade to no-ops returning None.
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 sample_rate: float = 1.0, level: int = 0):
        self.level = int(level)
        self.capacity = int(capacity)
        self.sample_rate = float(sample_rate)
        self._buf: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        # span ids: per-process random salt in the high bits + a counter
        # in the low 33, so ids from different processes never collide
        # when their journals are stitched by trace id
        self._span_salt = secrets.randbits(30) << 33
        self._ids = itertools.count(1)
        self._local = threading.local()
        self._sample_acc = 0.0
        self._epoch = time.perf_counter()
        # wall-clock anchor so exports can place spans in absolute time
        self.epoch_unix = time.time()
        self.dropped = 0  # spans suppressed by sampling (roots only)

    # -- state -------------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self.level > 0

    def configure(self, level: Optional[int] = None,
                  sample_rate: Optional[float] = None,
                  capacity: Optional[int] = None) -> "Tracer":
        if level is not None:
            self.level = int(level)
        if sample_rate is not None:
            self.sample_rate = float(sample_rate)
        if capacity is not None and int(capacity) != self.capacity:
            self.capacity = int(capacity)
            with self._lock:
                self._buf = deque(self._buf, maxlen=self.capacity)
        return self

    def _now(self) -> float:
        return time.perf_counter() - self._epoch

    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def current_span(self) -> Optional[Span]:
        """Innermost open span on THIS thread (None outside any span or
        under an unsampled root)."""
        st = self._stack()
        return st[-1] if st else None

    def _sampled(self) -> bool:
        """Deterministic counter-based root sampling: keeps exactly the
        configured fraction, no RNG."""
        if self.sample_rate >= 1.0:
            return True
        with self._lock:
            self._sample_acc += self.sample_rate
            if self._sample_acc >= 1.0:
                self._sample_acc -= 1.0
                return True
            self.dropped += 1
            return False

    # -- span lifecycle ----------------------------------------------------
    def start_span(self, name: str, parent: Optional[Span] = None,
                   detached: bool = False, **attrs) -> Optional[Span]:
        """Open a span. Context flows from ``parent`` when given, else
        from this thread's innermost open span. Detached spans skip the
        thread-local stack (cross-thread lifetimes) and must be ended via
        ``span.finish()``. Returns None when tracing is off or the root
        is sampled out."""
        if not self.enabled:
            return None
        if parent is None and not detached:
            st = self._stack()
            if st:
                parent = st[-1]
                if parent is None:  # inside an unsampled subtree
                    st.append(None)
                    return None
        if parent is None and not self._sampled():
            if not detached:
                self._stack().append(None)  # suppress the subtree
            return None
        trace_id = parent.trace_id if parent is not None \
            else _new_trace_id()
        sp = Span(name, self._span_salt | next(self._ids),
                  parent.span_id if parent is not None else None,
                  trace_id, self._now(), threading.get_ident(), self)
        if attrs:
            sp.attrs.update(attrs)
        if not detached:
            self._stack().append(sp)
        return sp

    def _end_span(self, sp: Span) -> None:
        if sp.end is not None:
            return  # idempotent: double-finish records once
        sp.end = self._now()
        with self._lock:
            self._buf.append(sp)

    def _pop(self, sp: Optional[Span]) -> None:
        st = self._stack()
        if st:
            top = st.pop()
            if top is not None:
                self._end_span(top)
        elif sp is not None:
            self._end_span(sp)

    @contextlib.contextmanager
    def span(self, name: str, **attrs) -> Iterator[Optional[Span]]:
        """Scoped span: nests under the current thread's open span.
        Yields the Span (or None when disabled/sampled out) so the body
        can attach attributes."""
        if not self.enabled:
            yield None
            return
        sp = self.start_span(name, **attrs)
        try:
            yield sp
        finally:
            self._pop(sp)

    def record(self, name: str, start: float, end: float,
               parent: Optional[Span] = None, **attrs) -> Optional[Span]:
        """Record an already-timed region (``start``/``end`` from
        ``perf_counter``) as a completed span — how batch-level work is
        attributed to each request riding the batch."""
        if not self.enabled:
            return None
        trace_id = parent.trace_id if parent is not None \
            else _new_trace_id()
        sp = Span(name, self._span_salt | next(self._ids),
                  parent.span_id if parent is not None else None,
                  trace_id, start - self._epoch,
                  threading.get_ident(), self)
        sp.attrs.update(attrs)
        sp.end = end - self._epoch
        with self._lock:
            self._buf.append(sp)
        return sp

    # -- cross-process context (W3C trace context) ------------------------
    def inject(self, span: Optional[Span] = None) -> Optional[str]:
        """Render ``span`` (default: this thread's current span) as a
        W3C ``traceparent`` header value, e.g.
        ``00-<32-hex trace id>-<16-hex span id>-01``. Returns None when
        tracing is off or there is no span to propagate — callers simply
        omit the header then."""
        sp = span if span is not None else self.current_span()
        if sp is None:
            return None
        return (f"00-{sp.trace_id & ((1 << 128) - 1):032x}"
                f"-{sp.span_id & ((1 << 64) - 1):016x}-01")

    @staticmethod
    def extract(header: Optional[str]) -> Optional[SpanContext]:
        """Parse a ``traceparent`` header into a :class:`SpanContext`
        usable as ``parent=``. An absent, malformed, all-zero, or
        explicitly-unsampled header yields None (start a fresh local
        trace) — this NEVER raises: a bad header from an arbitrary
        client must not fail the request carrying it."""
        if not header or not isinstance(header, str):
            return None
        parts = header.strip().split("-")
        if len(parts) < 4:
            return None
        ver, tid, sid, flags = parts[0], parts[1], parts[2], parts[3]
        if len(ver) != 2 or len(tid) != 32 or len(sid) != 16 \
                or len(flags) < 2:
            return None
        try:
            ver_i = int(ver, 16)
            tid_i = int(tid, 16)
            sid_i = int(sid, 16)
            flags_i = int(flags[:2], 16)
        except ValueError:
            return None
        if ver_i == 0xFF or tid_i == 0 or sid_i == 0:
            return None
        if not flags_i & 0x01:  # upstream sampled it out: fresh trace
            return None
        return SpanContext(tid_i, sid_i)

    # -- read side ---------------------------------------------------------
    def spans(self) -> List[Span]:
        """Snapshot of the completed-span ring buffer (oldest first)."""
        with self._lock:
            return list(self._buf)

    def drain(self) -> List[Span]:
        """Snapshot AND clear — exporters use this to checkpoint."""
        with self._lock:
            out = list(self._buf)
            self._buf.clear()
        return out

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)


# ---------------------------------------------------------------------------
# Process-global tracer + module-level conveniences
# ---------------------------------------------------------------------------
_global_tracer = Tracer()

try:  # seed from the flag plane (--trace_level / PADDLE_TPU_TRACE_LEVEL)
    from ..flags import FLAGS as _FLAGS

    _global_tracer.configure(level=_FLAGS.trace_level,
                             sample_rate=_FLAGS.trace_sample_rate,
                             capacity=_FLAGS.trace_buffer)
except Exception:  # pragma: no cover - flags unavailable standalone
    pass


def get_tracer() -> Tracer:
    return _global_tracer


def enable(level: int = 1, sample_rate: float = 1.0,
           capacity: Optional[int] = None) -> Tracer:
    """Turn on the global tracer (idempotent). Level 1 = span tracing,
    level 2 = additionally switch Executor.run to the per-op interpret
    path. Returns the tracer."""
    return _global_tracer.configure(level=level, sample_rate=sample_rate,
                                    capacity=capacity)


def disable() -> Tracer:
    return _global_tracer.configure(level=0)


def enabled() -> bool:
    return _global_tracer.enabled


def active_level() -> int:
    return _global_tracer.level


def span(name: str, **attrs):
    """``with trace.span("name", k=v) as sp:`` against the global
    tracer."""
    return _global_tracer.span(name, **attrs)


def start_span(name: str, parent: Optional[Span] = None,
               detached: bool = False, **attrs) -> Optional[Span]:
    return _global_tracer.start_span(name, parent=parent,
                                     detached=detached, **attrs)


def record(name: str, start: float, end: float,
           parent: Optional[Span] = None, **attrs) -> Optional[Span]:
    return _global_tracer.record(name, start, end, parent=parent, **attrs)


def current_span() -> Optional[Span]:
    return _global_tracer.current_span()


def inject(span: Optional[Span] = None) -> Optional[str]:
    """``traceparent`` header for ``span`` (default: the current span)
    against the global tracer; None when there is nothing to carry."""
    return _global_tracer.inject(span)


def extract(header: Optional[str]) -> Optional[SpanContext]:
    """Parse a ``traceparent`` header into a parent handle (or None) —
    never raises on malformed input."""
    return Tracer.extract(header)

"""Declarative serving SLOs with multi-window burn-rate alerting.

An :class:`SLO` states the objectives a serving fleet is operated
against — "99% of requests see their first token within ``ttft_ms``,
per-token decode latency under ``tpot_ms``, availability at least
``availability``" — and an :class:`SLOTracker` evaluates them
continuously from the :class:`~paddle_tpu.serving.metrics.MetricsRegistry`
histogram/counter plane (the fixed-bucket TTFT/TPOT histograms make the
attainment fraction exact up to bucket resolution, and — because bucket
counts merge by summation — the SAME evaluation is correct fleet-wide).

Alerting follows the SRE-workbook multi-window burn-rate recipe: the
error-budget burn rate (bad fraction divided by the budget fraction
``1 - target``) is computed over a short and a long sliding window; an
objective *alerts* only when BOTH windows burn above their thresholds —
the short window makes the alert fast, the long window keeps a brief
blip from paging. ``burn == 1`` means "spending exactly the budget";
``budget_remaining`` is the fraction of the lifetime error budget left.

    slo = SLO(ttft_ms=250.0, tpot_ms=50.0, availability=0.999)
    tracker = SLOTracker(slo)
    tracker.sample(registry.snapshot())   # each /metrics scrape
    tracker.status()                      # attainment / burn / alerts

Surfaced on ``/metrics`` (labeled gauges), ``/fleet/status`` (the
``slo`` key), and rendered by ``tools/fleetctl.py status``.
"""
from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Dict, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class SLO:
    """A serving service-level objective set. Latency objectives
    (``ttft_ms`` / ``tpot_ms``) are met when at least ``target`` of the
    observations fall under the threshold; ``availability`` is its own
    target (completed / (completed + failed)); ``freshness_s`` is the
    online-learning objective — the ``weights_staleness_s`` gauge (how
    many seconds the served weights trail the trainer's newest
    checkpoint, exported by :class:`paddle_tpu.online.Publisher`) must
    be under the threshold at ``target`` of the scrape samples, so a
    stalled publisher burns error budget exactly like a slow decode.
    Unset objectives are simply not evaluated."""

    ttft_ms: Optional[float] = None
    tpot_ms: Optional[float] = None
    availability: Optional[float] = None
    freshness_s: Optional[float] = None
    #: training objective: the goodput fraction (device-compute seconds
    #: over total attributed seconds, exported by
    #: :class:`paddle_tpu.trace.GoodputMeter` as the cumulative
    #: ``goodput_good_ms_total`` / ``goodput_total_ms_total`` counter
    #: pair) must stay at or above this value — badput (data stalls,
    #: compiles, checkpoint stalls, recovery) burns error budget under
    #: the same multi-window machinery as a slow decode
    goodput: Optional[float] = None
    target: float = 0.99
    #: (short, long) sliding burn-rate windows, seconds
    windows_s: Tuple[float, float] = (60.0, 300.0)
    #: burn-rate thresholds per window (both must exceed to alert)
    burn_thresholds: Tuple[float, float] = (14.4, 6.0)
    name: str = "serving"

    def objectives(self) -> Dict[str, dict]:
        out = {}
        if self.ttft_ms is not None:
            out["ttft"] = {"kind": "hist", "metric": "ttft",
                           "threshold_ms": float(self.ttft_ms),
                           "target": self.target}
        if self.tpot_ms is not None:
            out["tpot"] = {"kind": "hist", "metric": "tpot",
                           "threshold_ms": float(self.tpot_ms),
                           "target": self.target}
        if self.availability is not None:
            out["availability"] = {"kind": "counter",
                                   "target": float(self.availability)}
        if self.freshness_s is not None:
            out["freshness"] = {"kind": "gauge",
                                "metric": "weights_staleness_s",
                                "threshold_s": float(self.freshness_s),
                                "target": self.target}
        if self.goodput is not None:
            out["goodput"] = {"kind": "ratio",
                              "good": "goodput_good_ms_total",
                              "total": "goodput_total_ms_total",
                              "target": float(self.goodput)}
        return out

    def to_dict(self) -> dict:
        return {"name": self.name, "ttft_ms": self.ttft_ms,
                "tpot_ms": self.tpot_ms,
                "availability": self.availability,
                "freshness_s": self.freshness_s,
                "goodput": self.goodput, "target": self.target,
                "windows_s": list(self.windows_s),
                "burn_thresholds": list(self.burn_thresholds)}


def _hist_good_total(hist: Optional[dict],
                     threshold_ms: float) -> Tuple[int, int]:
    """(observations under threshold, total) from a snapshot histogram.
    The threshold is resolved to the smallest bucket bound >= it, so the
    answer is deterministic and, with thresholds chosen on (or near)
    bucket bounds, exact."""
    if not hist or not hist.get("counts"):
        return 0, 0
    bounds = hist.get("bounds_ms") or []
    counts = hist["counts"]
    good = 0.0
    for i, (bound, c) in enumerate(zip(bounds, counts)):
        if bound > threshold_ms * (1 + 1e-9):
            # partial credit for the straddling bucket keeps attainment
            # monotonic in the threshold even off bucket bounds
            prev = bounds[i - 1] if i > 0 else 0.0
            if threshold_ms > prev:
                good += c * (threshold_ms - prev) / (bound - prev)
            break
        good += c
    total = sum(counts)
    return int(round(good)), total


class SLOTracker:
    """Evaluates an :class:`SLO` over time from metrics snapshots.

    ``sample()`` appends cumulative (good, total) checkpoints per
    objective; ``status()`` differences them against the checkpoint
    nearest each window edge to get windowed burn rates. Sampling is
    driven by whoever scrapes metrics (every ``/metrics`` or
    ``/fleet/status`` render) — there is no thread of its own.
    """

    def __init__(self, slo: SLO, clock=time.monotonic,
                 max_samples: int = 4096):
        self.slo = slo
        self._clock = clock
        self._lock = threading.Lock()
        self._samples: deque = deque(maxlen=max_samples)
        # gauge objectives (freshness) are instantaneous per scrape, so
        # the tracker itself accumulates the cumulative good/total the
        # windowed differencing needs
        self._gauge_cum: Dict[str, list] = {}

    def _extract(self, snapshot: dict) -> Dict[str, Tuple[int, int]]:
        """Cumulative (good, total) per objective. Caller holds the
        lock (gauge accumulation mutates tracker state)."""
        out = {}
        hists = snapshot.get("hist") or {}
        counters = snapshot.get("counters") or {}
        gauges = snapshot.get("gauges") or {}
        for name, obj in self.slo.objectives().items():
            if obj["kind"] == "hist":
                out[name] = _hist_good_total(hists.get(obj["metric"]),
                                             obj["threshold_ms"])
            elif obj["kind"] == "gauge":
                cum = self._gauge_cum.setdefault(name, [0, 0])
                val = gauges.get(obj["metric"])
                if val is not None:  # absent until a publisher exports
                    cum[1] += 1
                    if float(val) <= obj["threshold_s"] * (1 + 1e-9):
                        cum[0] += 1
                out[name] = (cum[0], cum[1])
            elif obj["kind"] == "ratio":
                # already-cumulative counter pair (goodput ms over
                # total attributed ms): the windowed differencing
                # yields the window's goodput fraction directly
                out[name] = (int(counters.get(obj["good"], 0)),
                             int(counters.get(obj["total"], 0)))
            else:
                good = int(counters.get("completed", 0))
                out[name] = (good, good + int(counters.get("failed", 0)))
        return out

    def sample(self, snapshot: dict) -> None:
        """Checkpoint cumulative good/total per objective from a
        :meth:`MetricsRegistry.snapshot` (or fleet-merged) payload."""
        with self._lock:
            row = self._extract(snapshot)
            self._samples.append((self._clock(), row))

    def _window_rates(self, name: str, target: float,
                      now: float) -> Dict[str, dict]:
        """Per-window burn rates by differencing cumulative counts
        against the newest sample at or before the window edge."""
        newest_t, newest = self._samples[-1]
        g1, t1 = newest.get(name, (0, 0))
        out = {}
        budget = max(1e-9, 1.0 - target)
        for win in self.slo.windows_s:
            edge = now - win
            g0, t0 = 0, 0
            for ts, row in self._samples:
                if ts > edge:
                    break
                g0, t0 = row.get(name, (0, 0))
            good, total = g1 - g0, t1 - t0
            bad_frac = ((total - good) / total) if total > 0 else 0.0
            out[f"{int(win)}s"] = {
                "total": total,
                "bad_fraction": round(bad_frac, 6),
                "burn_rate": round(bad_frac / budget, 4),
            }
        return out

    def status(self, snapshot: Optional[dict] = None) -> dict:
        """Evaluate every objective: overall attainment, lifetime error
        budget remaining, windowed burn rates, and the multi-window
        alert verdict. Pass a fresh ``snapshot`` to sample-and-evaluate
        in one call (what the HTTP endpoints do)."""
        if snapshot is not None:
            self.sample(snapshot)
        now = self._clock()
        objectives = {}
        alerting = False
        with self._lock:
            have = len(self._samples) > 0
            for name, obj in self.slo.objectives().items():
                target = obj["target"]
                good, total = (self._samples[-1][1].get(name, (0, 0))
                               if have else (0, 0))
                attainment = (good / total) if total > 0 else 1.0
                budget = max(1e-9, 1.0 - target)
                consumed = (1.0 - attainment) / budget
                windows = (self._window_rates(name, target, now)
                           if have else {})
                burns = [w["burn_rate"] for w in windows.values()]
                obj_alert = (len(burns) == len(self.slo.burn_thresholds)
                             and all(b > thr for b, thr in
                                     zip(burns, self.slo.burn_thresholds)))
                alerting = alerting or obj_alert
                objectives[name] = {
                    "target": target,
                    "threshold_ms": obj.get("threshold_ms"),
                    "threshold_s": obj.get("threshold_s"),
                    "total": total,
                    "attainment": round(attainment, 6),
                    "error_budget_remaining": round(1.0 - consumed, 4),
                    "burn": windows,
                    "alerting": obj_alert,
                }
        return {"slo": self.slo.to_dict(), "objectives": objectives,
                "alerting": alerting}

    def publish_gauges(self, registry, status: Optional[dict] = None,
                       **labels) -> dict:
        """Export the evaluation as labeled gauges on a MetricsRegistry
        (``slo_attainment{objective=...}``,
        ``slo_error_budget_remaining{...}``,
        ``slo_burn_rate{objective=...,window=...}``,
        ``slo_alerting{...}``) so ``/metrics?format=prom`` carries the
        whole SLO plane. Extra ``labels`` ride every series — the
        multi-tenant registry publishes one burn-rate plane per tenant
        as ``slo_burn_rate{objective=...,tenant=...,window=...}``.
        Returns the status dict it published."""
        st = status or self.status()
        for name, obj in st["objectives"].items():
            registry.set_labeled("slo_attainment", obj["attainment"],
                                 objective=name, **labels)
            registry.set_labeled("slo_error_budget_remaining",
                                 obj["error_budget_remaining"],
                                 objective=name, **labels)
            registry.set_labeled("slo_alerting",
                                 1.0 if obj["alerting"] else 0.0,
                                 objective=name, **labels)
            for win, w in obj["burn"].items():
                registry.set_labeled("slo_burn_rate", w["burn_rate"],
                                     objective=name, window=win,
                                     **labels)
        return st

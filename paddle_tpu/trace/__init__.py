"""paddle_tpu.trace — structured span tracing and the unified telemetry
plane.

The observability islands (profiler timers, serving metrics, trainer
events) share one spine here: a hierarchical span tracer (thread-local
context, parent links, bounded ring buffer, deterministic sampling) that
the executor, trainer and serving stack all emit into, with Chrome
trace-event and JSONL exporters plus a training RunLog journal.

Quick start::

    from paddle_tpu import trace
    trace.enable(level=1)            # level 2 = per-op executor debug
    ... run things ...
    trace.export_chrome_trace("trace.json")   # chrome://tracing
    trace.export_jsonl("spans.jsonl")

Summarize offline with ``python tools/trace_summary.py trace.json``.
"""
from .tracer import (DEFAULT_CAPACITY, Span, SpanContext, Tracer,
                     active_level, current_span, disable, enable, enabled,
                     extract, get_tracer, inject, record, span, start_span)
from .export import (export_chrome_trace, export_jsonl, load_jsonl_spans,
                     load_trace_events, spans_to_chrome_events)
from .runlog import RunLog
from .device import (device_memory_stats, live_bytes,
                     per_device_memory_stats)
from .slo import SLO, SLOTracker
from .flight import (FlightRecorder, get_recorder,
                     install_signal_handler)
from .goodput import BUCKETS, GoodputMeter, program_flops

__all__ = [
    "DEFAULT_CAPACITY", "Span", "SpanContext", "Tracer", "RunLog",
    "active_level", "current_span", "disable", "enable", "enabled",
    "extract", "get_tracer", "inject", "record", "span", "start_span",
    "export_chrome_trace", "export_jsonl", "load_jsonl_spans",
    "load_trace_events",
    "spans_to_chrome_events", "device_memory_stats", "live_bytes",
    "per_device_memory_stats",
    "SLO", "SLOTracker",
    "FlightRecorder", "get_recorder", "install_signal_handler",
    "BUCKETS", "GoodputMeter", "program_flops",
]

"""Crash-safe flight recorder: a bounded, always-on black box.

Production postmortems die on "it fell over at 3am and the logs rotated".
The :class:`FlightRecorder` keeps three bounded rings that cost almost
nothing while everything is healthy:

- **events** — notable moments (completed requests, dispatch errors,
  breaker trips) appended via :meth:`note`;
- **metric snapshots** — time-series samples of a
  :class:`~paddle_tpu.serving.metrics.MetricsRegistry` (pages in use,
  prefix-hit tokens, COW copies, deferred admissions — gauges that were
  only ever point-in-time) via the throttled :meth:`maybe_sample`;
- **sources** — live state callbacks (engine slot tables, pool stats,
  last-N request timelines) registered weakly via :meth:`add_source`, so
  a dump captures the state AT the moment of failure.

:meth:`bundle` assembles those rings plus the global tracer's recent
spans into one JSON document; :meth:`dump` writes it to disk. Dumps are
produced automatically by the serving dispatch loop on an unhandled
executor/serving error (throttled), on ``SIGUSR1``
(:func:`install_signal_handler`), and on demand via the servers'
``/admin/flightdump`` endpoint — "attach the flight bundle" replaces
"try to reproduce it".
"""
from __future__ import annotations

import json
import os
import threading
import time
import weakref
from collections import deque
from typing import Callable, Dict, Optional

#: auto-dump throttle: one error-triggered dump per window, so a
#: crash-looping dispatch thread records the FIRST failure instead of
#: grinding the disk with thousands of identical bundles
DEFAULT_MIN_DUMP_INTERVAL_S = 30.0


class FlightRecorder:
    """Bounded always-on recorder; one process-global instance
    (:func:`get_recorder`) serves the stack, tests build private ones."""

    def __init__(self, events: int = 512, snapshots: int = 256,
                 spans: int = 2048,
                 min_dump_interval_s: float = DEFAULT_MIN_DUMP_INTERVAL_S):
        self.enabled = True
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=int(events))
        self._snapshots: deque = deque(maxlen=int(snapshots))
        self._span_tail = int(spans)
        self._sources: Dict[str, object] = {}
        self._source_ids = 0
        self._min_dump_interval_s = float(min_dump_interval_s)
        self._last_auto_dump = 0.0
        self._last_sample: Dict[int, float] = {}
        self.last_bundle: Optional[dict] = None
        self.dumps = 0

    # -- write side --------------------------------------------------------
    def note(self, kind: str, **data) -> None:
        """Append one event to the ring (cheap: a dict + deque append)."""
        if not self.enabled:
            return
        row = {"t_unix": time.time(), "kind": kind}
        row.update(data)
        with self._lock:
            self._events.append(row)

    def maybe_sample(self, registry, tag: str = "serving",
                     min_interval_s: float = 0.5) -> bool:
        """Sample a MetricsRegistry snapshot into the time-series ring,
        at most once per ``min_interval_s`` per registry — called from
        the engine tick loop, so gauges that were only ever
        point-in-time (pages in use, prefix hits, COW copies, deferred
        admissions) become a bounded history."""
        if not self.enabled:
            return False
        key = id(registry)
        now = time.monotonic()
        last = self._last_sample.get(key, 0.0)
        if now - last < min_interval_s:
            return False
        self._last_sample[key] = now
        snap = registry.snapshot()
        with self._lock:
            self._snapshots.append({
                "t_unix": time.time(), "tag": tag,
                "counters": snap.get("counters", {}),
                "gauges": snap.get("gauges", {}),
                "qps": snap.get("qps"),
            })
        return True

    def add_source(self, name: str, fn: Callable[[], dict],
                   weak: bool = True) -> str:
        """Register a live-state callback captured at dump time. Bound
        methods are held via ``weakref.WeakMethod`` by default so a
        registered engine can still be garbage collected; dead sources
        are pruned silently. Returns the (uniquified) source name."""
        with self._lock:
            self._source_ids += 1
            key = f"{name}#{self._source_ids}"
            if weak:
                try:
                    fn = weakref.WeakMethod(fn)  # type: ignore[assignment]
                except TypeError:
                    pass  # plain function: hold it strongly
            self._sources[key] = fn
        return key

    def remove_source(self, key: str) -> None:
        with self._lock:
            self._sources.pop(key, None)

    # -- read side ---------------------------------------------------------
    def bundle(self, reason: str, error: Optional[BaseException] = None,
               tracer=None) -> dict:
        """Assemble the flight bundle: recent spans (tail of the global
        tracer ring), the event + metric-snapshot rings, and every live
        source's state. Source failures are captured, never raised — a
        recorder must not crash the thing it is recording."""
        from .tracer import get_tracer

        tracer = tracer or get_tracer()
        spans = tracer.spans()[-self._span_tail:]
        with self._lock:
            events = list(self._events)
            snapshots = list(self._snapshots)
            sources = dict(self._sources)
        state = {}
        dead = []
        for key, fn in sources.items():
            target = fn() if isinstance(fn, weakref.WeakMethod) else fn
            if target is None:
                dead.append(key)
                continue
            try:
                state[key] = target()
            except Exception as exc:  # noqa: BLE001 - never crash a dump
                state[key] = {"error": repr(exc)[:200]}
        if dead:
            with self._lock:
                for key in dead:
                    self._sources.pop(key, None)
        doc = {
            "reason": reason,
            "t_unix": time.time(),
            "pid": os.getpid(),
            "error": repr(error)[:500] if error is not None else None,
            "trace": {
                "epoch_unix": tracer.epoch_unix,
                "level": tracer.level,
                "spans": [sp.to_dict() for sp in spans
                          if sp.end is not None],
            },
            "events": events,
            "metric_snapshots": snapshots,
            "state": state,
        }
        self.last_bundle = doc
        return doc

    def dump(self, reason: str, path: Optional[str] = None,
             error: Optional[BaseException] = None,
             tracer=None) -> Optional[str]:
        """Write a bundle to ``path`` (default:
        ``$PADDLE_TPU_FLIGHT_DIR/flight-<pid>-<reason>-<n>.json``, or
        the in-memory ``last_bundle`` only when no directory is
        configured). Returns the written path, or None."""
        doc = self.bundle(reason, error=error, tracer=tracer)
        self.dumps += 1
        if path is None:
            dirname = os.environ.get("PADDLE_TPU_FLIGHT_DIR")
            if not dirname:
                return None
            os.makedirs(dirname, exist_ok=True)
            path = os.path.join(
                dirname, f"flight-{os.getpid()}-"
                f"{''.join(c if c.isalnum() else '_' for c in reason)}"
                f"-{self.dumps}.json")
        try:
            with open(path, "w") as f:
                json.dump(doc, f)
        except OSError:
            return None
        return path

    def auto_dump(self, reason: str,
                  error: Optional[BaseException] = None) -> Optional[str]:
        """The error-path entry point: throttled (one per
        ``min_dump_interval_s``) so a crash loop records its first
        failure instead of flooding. Always refreshes ``last_bundle``;
        writes a file only when a flight dir is configured."""
        if not self.enabled:
            return None
        now = time.monotonic()
        if now - self._last_auto_dump < self._min_dump_interval_s:
            return None
        self._last_auto_dump = now
        self.note("auto_dump", reason=reason,
                  error=repr(error)[:200] if error else None)
        return self.dump(reason, error=error)


# ---------------------------------------------------------------------------
# process-global recorder + SIGUSR1
# ---------------------------------------------------------------------------
_global_recorder = FlightRecorder()


def get_recorder() -> FlightRecorder:
    return _global_recorder


def install_signal_handler(dirname: Optional[str] = None,
                           recorder: Optional[FlightRecorder] = None,
                           signum: Optional[int] = None) -> bool:
    """Dump a flight bundle on ``SIGUSR1`` — the operator's "tell me
    what you are doing RIGHT NOW" poke for a live process. Returns False
    (instead of raising) off the main thread or on platforms without the
    signal, so servers can call it unconditionally."""
    import signal as signal_mod

    recorder = recorder or _global_recorder
    signum = signum if signum is not None \
        else getattr(signal_mod, "SIGUSR1", None)
    if signum is None:
        return False
    if dirname:
        os.environ.setdefault("PADDLE_TPU_FLIGHT_DIR", dirname)

    def _handler(sig, frame):
        recorder.note("signal", signum=int(sig))
        recorder.dump("sigusr1")

    try:
        signal_mod.signal(signum, _handler)
    except ValueError:  # not the main thread
        return False
    return True

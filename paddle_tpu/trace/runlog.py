"""RunLog: a JSONL training-run journal driven by trainer events.

The reference trainer prints per-batch cost at ``--log_period`` and dumps
the global Stat table at pass end (Trainer.cpp:449
``globalStat.printAllStatus()``). RunLog is the machine-readable version:
hand one to ``SGD.train(..., run_log=...)`` (or call it yourself as an
event handler) and every iteration lands as one JSON line with cost,
metrics, wall time and examples/sec; every pass end lands with the pass
summary AND a snapshot of the profiler's global StatSet, so a run is
fully reconstructable offline (``tools/trace_summary.py --runlog``).
"""
from __future__ import annotations

import json
import time
from typing import IO, Optional, Union

from .. import event as evt
from .. import profiler


class RunLog:
    """Journals training progress to a JSONL file (or any writable).

    Parameters:
      sink: path or open file-like; lines are flushed as written.
      stat_set: StatSet dumped at EndPass (default: the profiler's
        process-global one — Trainer.cpp:449 parity).
      echo_stats: also print the StatSet table at pass end.
    """

    def __init__(self, sink: Union[str, IO], stat_set=None,
                 echo_stats: bool = False):
        if isinstance(sink, str):
            self._f: IO = open(sink, "w")
            self._owns = True
        else:
            self._f = sink
            self._owns = False
        self.stat_set = stat_set
        self.echo_stats = echo_stats
        self._iter_t0: Optional[float] = None
        # resolve-ordered clock: the previous EndIteration (or
        # BeginPass). Under ``async_depth>1`` BeginIteration k+1 fires
        # BEFORE EndIteration k resolves, so dispatch-anchored walls
        # measure only the resolve block and overstate throughput; the
        # interval between consecutive EndIterations is the true
        # per-step wall on both paths.
        self._last_end_t: Optional[float] = None
        self._pass_t0: Optional[float] = None
        self._pass_examples = 0
        self._mfu_ema: Optional[float] = None
        self._write({"type": "run_header", "t_unix": time.time()})

    # -- plumbing ----------------------------------------------------------
    def _write(self, row: dict) -> None:
        self._f.write(json.dumps(row) + "\n")
        self._f.flush()

    def close(self) -> None:
        if self._owns:
            self._f.close()

    def __enter__(self) -> "RunLog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- event handler -----------------------------------------------------
    def __call__(self, e) -> None:
        now = time.perf_counter()
        if isinstance(e, evt.BeginPass):
            self._pass_t0 = now
            self._last_end_t = now
            self._pass_examples = 0
            self._write({"type": "pass_begin", "pass": e.pass_id})
        elif isinstance(e, evt.BeginIteration):
            self._iter_t0 = now
        elif isinstance(e, evt.EndIteration):
            # resolve-ordered wall (time since the previous step
            # RESOLVED): correct under async pipelining, identical to
            # the dispatch-anchored wall when synchronous
            wall = (now - self._last_end_t) \
                if self._last_end_t is not None else None
            self._last_end_t = now
            bs = getattr(e, "batch_size", None)
            if bs:
                self._pass_examples += bs
            row = {"type": "iteration", "pass": e.pass_id,
                   "batch": e.batch_id, "cost": e.cost,
                   "metrics": e.metrics or {}}
            if wall is not None:
                row["wall_ms"] = round(wall * 1e3, 3)
                if bs and wall > 0:
                    row["examples_per_sec"] = round(bs / wall, 2)
            # goodput split + live MFU when the trainer measured them
            host_w = getattr(e, "host_wall_s", None)
            dev_w = getattr(e, "device_wall_s", None)
            mfu = getattr(e, "mfu", None)
            if host_w is not None:
                row["host_wall_ms"] = round(host_w * 1e3, 3)
            if dev_w is not None:
                row["device_wall_ms"] = round(dev_w * 1e3, 3)
            if mfu is not None:
                if self._mfu_ema is None:
                    self._mfu_ema = float(mfu)
                else:
                    self._mfu_ema = (0.1 * float(mfu)
                                     + 0.9 * self._mfu_ema)
                row["mfu"] = round(float(mfu), 6)
                row["mfu_ema"] = round(self._mfu_ema, 6)
            if bs:
                row["batch_size"] = bs
            self._write(row)
            self._iter_t0 = None
        elif isinstance(e, evt.EndPass):
            stats = self.stat_set if self.stat_set is not None \
                else profiler.global_stat
            wall = (now - self._pass_t0) if self._pass_t0 is not None \
                else None
            row = {"type": "pass_end", "pass": e.pass_id,
                   "metrics": e.metrics or {},
                   "stat_set": stats.as_dict()}
            if wall is not None:
                row["wall_s"] = round(wall, 3)
                if self._pass_examples and wall > 0:
                    row["examples_per_sec"] = round(
                        self._pass_examples / wall, 2)
            self._write(row)
            if self.echo_stats:
                print(stats.format(), flush=True)
        elif isinstance(e, evt.TestResult):
            self._write({"type": "test", "cost": e.cost,
                         "metrics": e.metrics or {}})

"""Device-memory gauge plane: jax live-bytes per local device.

TPU runtimes expose allocator stats per device (``bytes_in_use``,
``bytes_limit``, peak). The CPU backend usually exposes nothing — this
degrades to an empty dict there, so the serving /metrics endpoint can
call it unconditionally.
"""
from __future__ import annotations

from typing import Dict


def per_device_memory_stats() -> Dict[str, Dict[str, float]]:
    """Nested stats keyed by device id: allocator gauges when the
    backend reports them (TPU), else live-array bytes grouped by the
    device each array resides on — so sharded runs show PER-DEVICE HBM
    instead of one global number. Keys are string device ids ("0", "1",
    ...); values map stat name -> bytes."""
    out: Dict[str, Dict[str, float]] = {}
    try:
        import jax
        devices = jax.local_devices()
    except Exception:  # backend not initializable here
        return out
    have_alloc_stats = False
    for d in devices:
        try:
            ms = d.memory_stats()
        except Exception:
            ms = None
        if not ms:
            continue
        row = {}
        for stat in ("bytes_in_use", "bytes_limit", "peak_bytes_in_use"):
            if stat in ms:
                row[stat] = float(ms[stat])
        if row:
            have_alloc_stats = True
            out[str(d.id)] = row
    if have_alloc_stats:
        return out
    # CPU fallback: attribute jax.live_arrays() to their devices — the
    # serving-side twin of analyze_memory(plan=...)'s static per-device
    # estimate, measured instead of predicted
    try:
        import jax

        for a in jax.live_arrays():
            try:
                devs = list(a.devices())
            except Exception:
                continue
            if not devs:
                continue
            nbytes = float(a.size * a.dtype.itemsize) / len(devs)
            for d in devs:
                row = out.setdefault(str(d.id), {"live_bytes": 0.0})
                row["live_bytes"] = row.get("live_bytes", 0.0) + nbytes
    except Exception:
        pass
    return out


def device_memory_stats() -> Dict[str, float]:
    """Flat gauge dict keyed ``device<N>_<stat>`` (bytes): live bytes,
    limit and peak per local device, when the backend reports them."""
    out: Dict[str, float] = {}
    try:
        import jax
        devices = jax.local_devices()
    except Exception:  # backend not initializable here
        return out
    for d in devices:
        try:
            ms = d.memory_stats()
        except Exception:
            ms = None
        if not ms:
            continue
        for src, dst in (("bytes_in_use", "bytes_in_use"),
                         ("bytes_limit", "bytes_limit"),
                         ("peak_bytes_in_use", "peak_bytes_in_use")):
            if src in ms:
                out[f"device{d.id}_{dst}"] = float(ms[src])
    return out


def live_bytes() -> float:
    """Total bytes of live jax arrays on this process's devices.

    Prefers the allocator's ``bytes_in_use`` gauges (TPU); falls back to
    summing ``jax.live_arrays()`` where the backend reports no stats
    (CPU) — the witness plane the static peak-HBM estimator
    (analysis.memory) is cross-checked against in tier-1.
    """
    stats = device_memory_stats()
    in_use = [v for k, v in stats.items() if k.endswith("bytes_in_use")
              and not k.endswith("peak_bytes_in_use")]
    if in_use:
        return float(sum(in_use))
    try:
        import jax

        return float(sum(a.size * a.dtype.itemsize
                         for a in jax.live_arrays()))
    except Exception:
        return 0.0

"""Trace exporters: Chrome/Perfetto trace-event JSON and JSONL journals.

Chrome format (the ``chrome://tracing`` / Perfetto "JSON Array" flavor):
one complete event (``"ph": "X"``) per span, microsecond timestamps. Track
assignment is the part worth getting right on an async, multi-threaded
stack: rows are keyed by ``trace_id``, not OS thread, so a serving
request's queue wait (recorded from the HTTP thread) and its execute span
(recorded from the dispatch thread) nest on ONE row under the request
span, which is how the viewer shows per-request timelines. Span/parent
ids ride in ``args`` for machine consumers (tools/trace_summary.py).

The JSONL journal is the grep-able flavor: one span per line via
``Span.to_dict()``, plus a header line carrying the tracer's wall-clock
epoch so offline tooling can reconstruct absolute times.
"""
from __future__ import annotations

import json
from typing import IO, List, Optional, Union

from .tracer import Span, Tracer, get_tracer


def spans_to_chrome_events(spans: List[Span]) -> List[dict]:
    """Spans -> list of Chrome trace-event dicts (complete 'X' events)."""
    events = []
    for sp in spans:
        if sp.end is None:
            continue
        args = {"span_id": sp.span_id, "parent_id": sp.parent_id,
                "thread": sp.thread}
        args.update(sp.attrs)
        events.append({
            "name": sp.name,
            "ph": "X",
            "ts": round(sp.start * 1e6, 3),
            "dur": round((sp.end - sp.start) * 1e6, 3),
            "pid": 0,
            "tid": sp.trace_id,
            "cat": sp.name.split("/", 1)[0],
            "args": args,
        })
    events.sort(key=lambda e: (e["tid"], e["ts"], -e["dur"]))
    return events


def export_chrome_trace(path_or_file: Union[str, IO],
                        tracer: Optional[Tracer] = None,
                        drain: bool = False) -> int:
    """Write the tracer's completed spans as Chrome trace-event JSON
    (object form: ``{"traceEvents": [...], ...}``). Load the file in
    chrome://tracing, Perfetto, or ``tools/trace_summary.py``. Returns
    the number of events written."""
    tracer = tracer or get_tracer()
    spans = tracer.drain() if drain else tracer.spans()
    doc = {
        "traceEvents": spans_to_chrome_events(spans),
        "displayTimeUnit": "ms",
        "otherData": {"producer": "paddle_tpu.trace",
                      "epoch_unix": tracer.epoch_unix},
    }
    if isinstance(path_or_file, str):
        with open(path_or_file, "w") as f:
            json.dump(doc, f)
    else:
        json.dump(doc, path_or_file)
    return len(doc["traceEvents"])


def export_jsonl(path_or_file: Union[str, IO],
                 tracer: Optional[Tracer] = None,
                 drain: bool = False, append: bool = False) -> int:
    """Write completed spans as a JSONL run journal (one span per line,
    preceded by a ``{"type": "trace_header", ...}`` line). Returns the
    number of span lines written."""
    tracer = tracer or get_tracer()
    spans = tracer.drain() if drain else tracer.spans()

    def _write(f) -> int:
        f.write(json.dumps({"type": "trace_header",
                            "epoch_unix": tracer.epoch_unix,
                            "spans": len(spans)}) + "\n")
        n = 0
        for sp in spans:
            if sp.end is None:
                continue
            row = sp.to_dict()
            row["type"] = "span"
            f.write(json.dumps(row) + "\n")
            n += 1
        return n

    if isinstance(path_or_file, str):
        with open(path_or_file, "a" if append else "w") as f:
            return _write(f)
    return _write(path_or_file)


def load_jsonl_spans(path: str) -> List[dict]:
    """Read a JSONL journal back as span rows with ABSOLUTE wall-clock
    times (the header's ``epoch_unix`` plus each span's relative
    seconds) and the source file tagged — the unit
    ``tools/trace_summary.py --distributed`` stitches across processes
    by trace id."""
    import os as _os

    epoch = 0.0
    rows: List[dict] = []
    src = _os.path.basename(path)
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue
            kind = row.get("type")
            if kind == "trace_header":
                epoch = float(row.get("epoch_unix") or 0.0)
            elif kind == "span" and row.get("end_s") is not None:
                rows.append({
                    "name": row.get("name", "?"),
                    "trace_id": row.get("trace_id") or 0,
                    "span_id": row.get("span_id"),
                    "parent_id": row.get("parent_id"),
                    "start": epoch + float(row["start_s"]),
                    "end": epoch + float(row["end_s"]),
                    "attrs": row.get("attrs") or {},
                    "source": src,
                })
    return rows


def load_trace_events(path: str) -> List[dict]:
    """Read either export format back into a flat list of event dicts
    with ``name``/``ts``/``dur``(us)/``args`` keys — the
    tools/trace_summary.py input contract."""
    with open(path) as f:
        first = f.readline()
        f.seek(0)
        jsonl = False
        try:  # JSONL starts with a one-line trace_header/span row
            row = json.loads(first)
            jsonl = isinstance(row, dict) and row.get("type") in (
                "trace_header", "span")
        except json.JSONDecodeError:
            pass  # multi-line chrome JSON
        if not jsonl:
            doc = json.load(f)
            events = doc.get("traceEvents", doc) if isinstance(doc, dict) \
                else doc
            return [e for e in events if e.get("ph", "X") == "X"]
        events = []
        for line in f:
            line = line.strip()
            if not line:
                continue
            row = json.loads(line)
            if row.get("type") != "span" or row.get("end_s") is None:
                continue
            events.append({
                "name": row["name"],
                "ts": row["start_s"] * 1e6,
                "dur": (row["end_s"] - row["start_s"]) * 1e6,
                "tid": row.get("trace_id", 0),
                "args": dict(row.get("attrs") or {},
                             span_id=row.get("span_id"),
                             parent_id=row.get("parent_id")),
            })
        return events

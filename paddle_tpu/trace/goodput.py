"""Goodput/badput accounting and live MFU for the training plane.

Large-scale training reports (Megatron-LM, PaLM) make *goodput* — the
fraction of wall-clock spent doing productive device compute —
first-class, because at fleet scale the dominant losses live *between*
steps: data stalls, fresh compiles, checkpoint stalls, master waits and
recovery rollbacks, not the matmuls themselves. ``GoodputMeter`` is the
one accumulator the trainer loops drive so every second of a run is
attributable to exactly one bucket:

=================  =====================================================
bucket             what it measures
=================  =====================================================
device_compute     time blocked on device results (the goodput)
host_dispatch      python-side dispatch/bookkeeping between steps
data_wait          blocked on the reader / feed pipeline
fresh_compile      first-compilation of a new program shape
checkpoint_stall   step loop stalled on checkpoint save/commit
master_wait        elastic trainer idle on the master queue (NO_TASK,
                   task RPCs, heartbeats)
recovery_rollback  fenced-rejoin restore + requeued-tail bookkeeping
=================  =====================================================

The meter is deliberately *explicit* — trainer code times its own
regions via :meth:`measure`/:meth:`account` rather than re-deriving
walls from the span ring, so accounting stays correct whether or not
span tracing is enabled and costs one clock read per region.

Live MFU: :meth:`set_program_flops` (from
``analysis.analyze_memory(...).total_flops``) plus per-step
:meth:`note_step` device walls yield achieved-FLOPs/s over the
device peak (v5e roofline by default) as an instantaneous gauge and an
EMA — the ROADMAP north star measured continuously instead of
bench-only.

Publishing: :meth:`publish` pushes ``goodput_seconds_total{bucket=...}``
labeled series, ``goodput_fraction``/``mfu`` gauges and the cumulative
``goodput_good_ms_total``/``goodput_total_ms_total`` counter pair (the
``goodput`` SLO objective's ratio source) into a
``serving.MetricsRegistry``; :meth:`publish_stats` mirrors the buckets
into a profiler ``StatSet`` so pass-end runlog rows and
``tools/trace_summary.py --goodput`` see them with zero coupling.
"""
from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, Optional

# Ordered: the first bucket is the goodput numerator.
BUCKETS = (
    "device_compute",
    "host_dispatch",
    "data_wait",
    "fresh_compile",
    "checkpoint_stall",
    "master_wait",
    "recovery_rollback",
)

#: default EMA smoothing for the mfu gauge
MFU_EMA_ALPHA = 0.1


class GoodputMeter:
    """Cumulative per-bucket wall accounting + live MFU for one run.

    Thread-safe: the elastic trainer's stream reader accounts
    master_wait from the feed thread while the step loop accounts
    compute buckets.
    """

    def __init__(self, peak_flops: Optional[float] = None,
                 ema_alpha: float = MFU_EMA_ALPHA):
        if peak_flops is None:
            from ..analysis.costmodel import V5E_PEAK_FLOPS
            peak_flops = V5E_PEAK_FLOPS
        self.peak_flops = float(peak_flops)
        self.ema_alpha = float(ema_alpha)
        self._lock = threading.Lock()
        self._seconds: Dict[str, float] = {b: 0.0 for b in BUCKETS}
        self._program_flops: Optional[float] = None
        self._steps = 0
        self._mfu: Optional[float] = None
        self._mfu_ema: Optional[float] = None
        # already-published cumulative ms (registry counters are
        # monotonic, so publish() incs only the delta)
        self._pub_good_ms = 0
        self._pub_total_ms = 0

    # -- accounting --------------------------------------------------
    def account(self, bucket: str, dt: float) -> None:
        """Add ``dt`` seconds to ``bucket`` (negative deltas clamp to 0)."""
        if bucket not in self._seconds:
            raise KeyError(f"unknown goodput bucket: {bucket!r}")
        if dt <= 0.0:
            return
        with self._lock:
            self._seconds[bucket] += dt

    @contextlib.contextmanager
    def measure(self, bucket: str):
        """Time a region into ``bucket``."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.account(bucket, time.perf_counter() - t0)

    def move(self, src: str, dst: str, dt: float) -> None:
        """Re-attribute ``dt`` seconds from ``src`` to ``dst`` (e.g. a
        fresh compile discovered inside a timed dispatch region). Moves
        at most what ``src`` holds."""
        if dt <= 0.0:
            return
        with self._lock:
            dt = min(dt, self._seconds[src])
            self._seconds[src] -= dt
            self._seconds[dst] += dt

    def bucket_seconds(self, bucket: str) -> float:
        with self._lock:
            return self._seconds[bucket]

    # -- MFU ---------------------------------------------------------
    def set_program_flops(self, flops: Optional[float]) -> None:
        """Per-step program FLOPs (``analyze_memory(...).total_flops``);
        None/<=0 disables MFU."""
        with self._lock:
            self._program_flops = (float(flops)
                                   if flops and flops > 0 else None)

    def note_step(self, device_s: float) -> Optional[float]:
        """Record one step's measured device wall; returns the step's
        MFU (None when flops unknown or the wall is degenerate)."""
        with self._lock:
            self._steps += 1
            if (self._program_flops is None or device_s <= 0.0
                    or self.peak_flops <= 0.0):
                return None
            mfu = self._program_flops / device_s / self.peak_flops
            self._mfu = mfu
            if self._mfu_ema is None:
                self._mfu_ema = mfu
            else:
                a = self.ema_alpha
                self._mfu_ema = a * mfu + (1.0 - a) * self._mfu_ema
            return mfu

    @property
    def mfu(self) -> Optional[float]:
        with self._lock:
            return self._mfu

    @property
    def mfu_ema(self) -> Optional[float]:
        with self._lock:
            return self._mfu_ema

    @property
    def steps(self) -> int:
        with self._lock:
            return self._steps

    # -- readout -----------------------------------------------------
    def total_seconds(self) -> float:
        with self._lock:
            return sum(self._seconds.values())

    def goodput_fraction(self) -> Optional[float]:
        """device_compute / total, None before any accounting."""
        with self._lock:
            total = sum(self._seconds.values())
            if total <= 0.0:
                return None
            return self._seconds["device_compute"] / total

    def snapshot(self) -> dict:
        """JSON-safe cumulative view (seconds per bucket, total,
        goodput fraction, steps, mfu + ema)."""
        with self._lock:
            total = sum(self._seconds.values())
            return {
                "buckets": {b: round(self._seconds[b], 6)
                            for b in BUCKETS},
                "total_s": round(total, 6),
                "goodput": (round(self._seconds["device_compute"]
                                  / total, 4) if total > 0 else None),
                "steps": self._steps,
                "mfu": (round(self._mfu, 4)
                        if self._mfu is not None else None),
                "mfu_ema": (round(self._mfu_ema, 4)
                            if self._mfu_ema is not None else None),
            }

    # -- publication -------------------------------------------------
    def publish(self, registry, **labels) -> None:
        """Push the current state into a ``serving.MetricsRegistry``:
        labeled ``goodput_seconds_total{bucket=...}`` series, the
        ``goodput_fraction``/``mfu``/``mfu_ema`` gauges, and the
        monotonic ``goodput_good_ms_total``/``goodput_total_ms_total``
        counter pair the SLO ratio objective differentiates. Extra
        ``labels`` ride every labeled sample (e.g. ``trainer="t0"``)."""
        with self._lock:
            seconds = dict(self._seconds)
            mfu, ema = self._mfu, self._mfu_ema
            total = sum(seconds.values())
            good_ms = int(seconds["device_compute"] * 1e3)
            total_ms = int(total * 1e3)
            d_good = good_ms - self._pub_good_ms
            d_total = total_ms - self._pub_total_ms
            self._pub_good_ms, self._pub_total_ms = good_ms, total_ms
        for b in BUCKETS:
            registry.set_labeled("goodput_seconds_total", seconds[b],
                                 bucket=b, **labels)
        if total > 0:
            registry.set_gauge("goodput_fraction",
                               seconds["device_compute"] / total)
        if mfu is not None:
            registry.set_gauge("mfu", mfu)
        if ema is not None:
            registry.set_gauge("mfu_ema", ema)
        if d_good > 0:
            registry.inc("goodput_good_ms_total", d_good)
        if d_total > 0:
            registry.inc("goodput_total_ms_total", d_total)

    def publish_stats(self, stat_set, prefix: str = "goodput/") -> None:
        """Mirror cumulative bucket seconds into a profiler ``StatSet``
        as ``goodput/<bucket>`` timer entries (cumulative: each call
        adds only the un-mirrored delta), so pass-end runlog rows carry
        the waterfall."""
        with self._lock:
            seconds = dict(self._seconds)
        mirrored = getattr(self, "_mirrored", None)
        if mirrored is None:
            mirrored = self._mirrored = {b: 0.0 for b in BUCKETS}
        for b in BUCKETS:
            delta = seconds[b] - mirrored[b]
            if delta > 0.0:
                stat_set.add(prefix + b, delta)
                mirrored[b] = seconds[b]

    def telemetry(self, last_step_wall_s: Optional[float] = None) -> dict:
        """Compact heartbeat payload for the master's straggler plane."""
        snap = self.snapshot()
        out = {"steps": snap["steps"], "goodput": snap["goodput"],
               "mfu": snap["mfu_ema"] or snap["mfu"]}
        if last_step_wall_s is not None:
            out["step_wall_s"] = round(float(last_step_wall_s), 6)
        return out


def program_flops(program, feed_names=(), fetch_names=(), scope=None,
                  batch_size=1, plan=None) -> Optional[float]:
    """Best-effort per-step FLOPs from the calibrated cost model
    (``analysis.analyze_memory``); None when the program can't be
    priced — MFU simply stays off."""
    try:
        from ..analysis import analyze_memory
        ana = analyze_memory(program, feed_names=tuple(feed_names),
                             fetch_names=tuple(fetch_names), scope=scope,
                             batch_size=batch_size, include_costs=True,
                             plan=plan)
        flops = float(ana.total_flops)
        return flops if flops > 0 else None
    except Exception:
        return None

"""Parameter initializers — emit init ops into the startup program.

Mirrors /root/reference/python/paddle/v2/fluid/initializer.py (Constant,
Uniform, Normal, Xavier, MSRA): each initializer appends one op to the
startup program; running the startup program materialises all parameters on
device in a single compiled computation.
"""
from __future__ import annotations

import math

import numpy as np


class Initializer:
    def __call__(self, var, block):
        raise NotImplementedError


class ConstantInitializer(Initializer):
    def __init__(self, value: float = 0.0):
        self.value = value

    def __call__(self, var, block):
        block.append_op(
            "fill_constant",
            outputs={"Out": [var.name]},
            attrs={"shape": list(var.shape), "dtype": str(var.dtype),
                   "value": float(self.value)},
        )


class UniformInitializer(Initializer):
    def __init__(self, low: float = -1.0, high: float = 1.0, seed: int = 0):
        self.low, self.high, self.seed = low, high, seed

    def __call__(self, var, block):
        block.append_op(
            "uniform_random",
            outputs={"Out": [var.name]},
            attrs={"shape": list(var.shape), "dtype": str(var.dtype),
                   "min": self.low, "max": self.high, "seed": self.seed},
        )


class NormalInitializer(Initializer):
    def __init__(self, loc: float = 0.0, scale: float = 1.0, seed: int = 0):
        self.mean, self.std, self.seed = loc, scale, seed

    def __call__(self, var, block):
        block.append_op(
            "gaussian_random",
            outputs={"Out": [var.name]},
            attrs={"shape": list(var.shape), "dtype": str(var.dtype),
                   "mean": self.mean, "std": self.std, "seed": self.seed},
        )


class TruncatedNormalInitializer(Initializer):
    def __init__(self, loc: float = 0.0, scale: float = 1.0, seed: int = 0):
        self.mean, self.std, self.seed = loc, scale, seed

    def __call__(self, var, block):
        block.append_op(
            "truncated_gaussian_random",
            outputs={"Out": [var.name]},
            attrs={"shape": list(var.shape), "dtype": str(var.dtype),
                   "mean": self.mean, "std": self.std, "seed": self.seed},
        )


def _fans(var):
    shape = var.shape
    if len(shape) == 2:
        return shape[0], shape[1]
    if len(shape) == 4:
        # conv filter OIHW: receptive field * channels
        rf = shape[2] * shape[3]
        return shape[1] * rf, shape[0] * rf
    n = int(np.prod(shape))
    return n, n


class XavierInitializer(Initializer):
    """Glorot init (initializer.py Xavier in the reference)."""

    def __init__(self, uniform: bool = True, fan_in=None, fan_out=None, seed: int = 0):
        self.uniform, self.fan_in, self.fan_out, self.seed = uniform, fan_in, fan_out, seed

    def __call__(self, var, block):
        fin, fout = _fans(var)
        fin = self.fan_in if self.fan_in is not None else fin
        fout = self.fan_out if self.fan_out is not None else fout
        if self.uniform:
            limit = math.sqrt(6.0 / (fin + fout))
            UniformInitializer(-limit, limit, self.seed)(var, block)
        else:
            std = math.sqrt(2.0 / (fin + fout))
            NormalInitializer(0.0, std, self.seed)(var, block)


class MSRAInitializer(Initializer):
    """He/Kaiming init (initializer.py MSRA in the reference)."""

    def __init__(self, uniform: bool = True, fan_in=None, seed: int = 0):
        self.uniform, self.fan_in, self.seed = uniform, fan_in, seed

    def __call__(self, var, block):
        fin, _ = _fans(var)
        fin = self.fan_in if self.fan_in is not None else fin
        if self.uniform:
            limit = math.sqrt(6.0 / fin)
            UniformInitializer(-limit, limit, self.seed)(var, block)
        else:
            std = math.sqrt(2.0 / fin)
            NormalInitializer(0.0, std, self.seed)(var, block)


# fluid-style aliases
Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
TruncatedNormal = TruncatedNormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer

"""ParamAttr — per-parameter configuration.

Mirrors /root/reference/python/paddle/v2/fluid/param_attr.py: name,
initializer, learning-rate multiplier, regularizer, trainable flag.
"""
from __future__ import annotations

from .initializer import Initializer


class ParamAttr:
    def __init__(
        self,
        name: str = None,
        initializer: Initializer = None,
        learning_rate: float = 1.0,
        regularizer=None,
        trainable: bool = True,
        gradient_clip=None,
    ):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.gradient_clip = gradient_clip

    @staticmethod
    def to_attr(arg) -> "ParamAttr":
        if arg is None:
            return ParamAttr()
        if isinstance(arg, ParamAttr):
            return arg
        if isinstance(arg, str):
            return ParamAttr(name=arg)
        if isinstance(arg, Initializer):
            return ParamAttr(initializer=arg)
        if arg is False:
            return None  # explicit "no parameter" (e.g. bias_attr=False)
        raise TypeError(f"cannot interpret {arg!r} as ParamAttr")

"""ParamAttr — per-parameter configuration.

Mirrors /root/reference/python/paddle/v2/fluid/param_attr.py: name,
initializer, learning-rate multiplier, regularizer, trainable flag.
"""
from __future__ import annotations

from .initializer import Initializer


class StaticPruningHook:
    """Updater hook (reference ParameterUpdaterHook.cpp StaticPruningHook):
    a fixed mask keeping the largest-|w| (1 - sparsity_ratio) fraction of
    the INITIAL weights, re-applied after every optimizer update."""

    def __init__(self, sparsity_ratio: float = 0.6):
        if not 0.0 <= sparsity_ratio < 1.0:
            raise ValueError(f"sparsity_ratio must be in [0, 1), got "
                             f"{sparsity_ratio}")
        self.sparsity_ratio = float(sparsity_ratio)


def Hook(type: str, sparsity_ratio: float = 0.6):
    """HookConfig-style factory (reference ParameterUpdaterHook.cpp
    createImpl: 'pruning' is the only registered type)."""
    if type != "pruning":
        raise ValueError(f"unknown updater hook type {type!r}")
    return StaticPruningHook(sparsity_ratio)


class ParamAttr:
    def __init__(
        self,
        name: str = None,
        initializer: Initializer = None,
        learning_rate: float = 1.0,
        regularizer=None,
        trainable: bool = True,
        gradient_clip=None,
        update_hooks=None,
    ):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.gradient_clip = gradient_clip
        if update_hooks is not None and not isinstance(update_hooks,
                                                       (list, tuple)):
            update_hooks = [update_hooks]
        self.update_hooks = list(update_hooks or [])

    @staticmethod
    def to_attr(arg) -> "ParamAttr":
        if arg is None:
            return ParamAttr()
        if isinstance(arg, ParamAttr):
            return arg
        if isinstance(arg, str):
            return ParamAttr(name=arg)
        if isinstance(arg, Initializer):
            return ParamAttr(initializer=arg)
        if arg is False:
            return None  # explicit "no parameter" (e.g. bias_attr=False)
        raise TypeError(f"cannot interpret {arg!r} as ParamAttr")

"""Stop-sequence matching: host-side truncation of the decode stream.

A request may carry token-id stop sequences (``SamplingParams.stop``).
After every emitted token the engine asks the matcher whether the
generated tail now ends with any stop sequence; on a match the request
finishes and the returned ids are truncated BEFORE the match (the stop
sequence itself is not returned — the OpenAI-style contract). Matching
is pure host bookkeeping over the generated list, so a stop can land
anywhere — including mid-page on the paged cache, where the already-
written K/V rows past the truncation point are simply released with the
request's pages.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple


class StopMatcher:
    """Incremental matcher over one request's generated tokens."""

    __slots__ = ("sequences", "_max_len")

    def __init__(self, sequences: Sequence[Tuple[int, ...]]):
        self.sequences = tuple(tuple(int(t) for t in s)
                               for s in (sequences or ()))
        self._max_len = max((len(s) for s in self.sequences), default=0)

    def __bool__(self) -> bool:
        return bool(self.sequences)

    def match(self, generated: Sequence[int]) -> Optional[int]:
        """If ``generated`` now ENDS with a stop sequence, return the
        truncation length (tokens to keep, i.e. the match start);
        otherwise None. Longest match wins when several end here."""
        if not self.sequences:
            return None
        n = len(generated)
        best = None
        for seq in self.sequences:
            m = len(seq)
            if m <= n and tuple(generated[n - m:]) == seq:
                keep = n - m
                if best is None or keep < best:
                    best = keep
        return best

"""paddle_tpu.decoding — the decode platform.

What turns "an LM server" into a decode platform: policy moves from the
engine to the REQUEST, and every policy rides the same compiled step.

- :class:`SamplingParams` — per-request temperature / top-k / top-p /
  seed / max_tokens / stop sequences, carried as device arrays gathered
  per slot inside the one decode computation: mixed greedy-and-sampled
  batches keep the zero-recompile steady state, and sampled tokens are a
  pure function of (request, seed) — invariant to batch composition,
  tick interleaving, and fleet hedging.
- :class:`LogitsProcessor` / :class:`JsonSchemaMask` — the per-step
  token-mask hook (host-computed [vocab] rows fed per tick):
  grammar-constrained decoding is a mask away once the hook exists.
- :class:`StopMatcher` — token-sequence stops with mid-page truncation.
- :class:`BeamJob` (``engine.generate_beam`` / ``beam_size`` request
  meta) — beam search as paged-cache forks: a hypothesis fork is a
  refcounted block-table copy with copy-on-write on divergence, so beams
  share their whole common prefix in HBM; token-exact against the fused
  ``transformer_stack_beam_search`` reference.
- :class:`Seq2SeqGenerationEngine` — the encoder-decoder (NMT) config:
  cross-attention K/V computed ONCE at admission into a slot-resident
  cache alongside the self-attention page pool; beam forks SHARE the
  parent's cross-KV row (it is read-only after admission).
"""
from .beam import BeamJob
from .masks import JsonSchemaMask, LogitsProcessor, TokenBanMask
from .params import BeamParams, SamplingParams
from .stops import StopMatcher

__all__ = [
    "SamplingParams", "BeamParams", "BeamJob", "StopMatcher",
    "LogitsProcessor", "TokenBanMask", "JsonSchemaMask",
    "Seq2SeqSpec", "Seq2SeqGenerationEngine",
]


def __getattr__(name):  # lazy: seq2seq imports serving (cycle-free)
    if name in ("Seq2SeqSpec", "Seq2SeqGenerationEngine"):
        from . import seq2seq

        return getattr(seq2seq, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

"""Seq2seq (NMT) serving: the encoder-decoder GenerationEngine config.

:class:`Seq2SeqGenerationEngine` extends the paged continuous batcher
with the encoder-decoder split:

- **Admission runs the encoder once — pooled.** A request carries a
  SOURCE sentence; admission buckets it and QUEUES the encoder pass,
  and the queue flushes as bucket-padded batches (one
  ``transformer_encdec_encode`` call per source bucket per admission
  round, padded to ``encode_batch_buckets``) before anything attends
  the rows. The per-layer cross-attention K/V parks in a slot-resident
  cache ``[L, slots+1, Hkv, Ts, dh]`` (row ``slots`` is scrap) next to
  the self-attention page pool — the analysis plane prices both.
- **Decode is the paged loop plus one cross read per layer.** The
  decoder is the stacked LM (same weight contract) whose
  ``transformer_stack_cross_decode`` step additionally attends the
  request's parked encoder rows via a per-slot ``XSlot`` index.
- **Beam forks share the source.** The cross cache is read-only after
  admission, so a hypothesis fork bumps a refcount on its parent's
  cross row instead of copying [L, Hkv, Ts, dh] bytes — K beams of one
  translation carry ONE copy of the source K/V (and share their target
  prefix pages through the usual copy-on-write fork).

Prefix sharing is force-disabled: decoder K/V depend on the source
through cross-attention, so pages are NOT reusable across requests with
different sources (the sharing contract would silently serve another
sentence's translation state).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..core.program import Program, program_guard
from ..layers import data as data_layer
from ..layers.layer_helper import LayerHelper
from ..serving.batcher import Request
from ..serving.errors import BadRequestError
from ..serving.generation import (LMSpec, PAGED_CACHE_K, PAGED_CACHE_V,
                                  PagedGenerationEngine)

CROSS_K = "serving.cross_k"
CROSS_V = "serving.cross_v"


@dataclasses.dataclass
class Seq2SeqSpec:
    """Hyperparameters of the transformer NMT model (the
    ``models.shared_nmt_params`` weight contract)."""

    src_vocab_size: int
    tgt_vocab_size: int
    d_model: int
    n_layers: int
    num_heads: int
    num_kv_heads: Optional[int] = None
    max_src_len: int = 64
    max_tgt_len: int = 64
    d_ff: Optional[int] = None

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads or self.num_heads

    @property
    def head_dim(self) -> int:
        return self.d_model // self.num_heads

    def lm_spec(self) -> LMSpec:
        """The decoder viewed as a stacked LM (what the base engine
        machinery sizes its programs and pools by)."""
        return LMSpec(vocab_size=self.tgt_vocab_size,
                      d_model=self.d_model, n_layers=self.n_layers,
                      num_heads=self.num_heads,
                      num_kv_heads=self.num_kv_heads,
                      max_len=self.max_tgt_len, d_ff=self.d_ff)


def _default_src_buckets(tsmax: int) -> List[int]:
    buckets, b = [], 8
    while b < tsmax:
        buckets.append(b)
        b *= 2
    buckets.append(tsmax)
    return sorted(set(buckets))


class Seq2SeqGenerationEngine(PagedGenerationEngine):
    """Continuous batching for encoder-decoder generation; see the
    module docstring. Payloads are ``{"src": [ids]}`` with an optional
    ``"prompt"`` target prefix (default ``[bos_id]``); everything else —
    per-request SamplingParams, stop sequences, token masks, beam
    requests, warmup manifests, metrics — is inherited from the decode
    platform."""

    _cache_names = (PAGED_CACHE_K, PAGED_CACHE_V, CROSS_K, CROSS_V)

    def __init__(self, spec: Seq2SeqSpec, scope=None, *,
                 bos_id: int = 0,
                 src_buckets: Optional[Sequence[int]] = None,
                 encode_batch_buckets: Optional[Sequence[int]] = None,
                 beam_width: int = 4, **kw):
        self.seq2seq = spec
        self.bos_id = int(bos_id)
        self.src_buckets = sorted(set(
            min(int(b), spec.max_src_len)
            for b in (src_buckets
                      or _default_src_buckets(spec.max_src_len))))
        kw.pop("prefix_sharing", None)  # unsound across sources
        super().__init__(spec.lm_spec(), scope, beam_width=beam_width,
                         prefix_sharing=False, **kw)
        # encoder-pool batching: sources admitted in one admission round
        # are encoded together, padded to these batch buckets (so the
        # steady state compiles len(src_buckets) x len(batch buckets)
        # encode programs and nothing else). (1,) restores the
        # encode-per-request behavior token-exactly.
        self.encode_batch_buckets = sorted(set(
            max(1, min(int(b), self.slots))
            for b in (encode_batch_buckets or (1, 2, 4, 8))))

    # -- cross-KV cache ----------------------------------------------------
    def _init_cache(self):
        import jax.numpy as jnp

        super()._init_cache()
        s = self.seq2seq
        # row `slots` is the scrap row (vacant decode slots attend it)
        shape = (s.n_layers, self.slots + 1, s.kv_heads, s.max_src_len,
                 s.head_dim)
        self.scope.set(CROSS_K, jnp.zeros(shape, jnp.float32))
        self.scope.set(CROSS_V, jnp.zeros(shape, jnp.float32))
        # host-side cross-row accounting: a request takes one row at
        # admission; beam forks share it by refcount
        self._xrow_free = list(range(self.slots - 1, -1, -1))
        self._xrow_ref = np.zeros(self.slots, np.int32)
        self._xrow_len = np.ones(self.slots, np.int32)
        self._encode_progs: Dict[int, tuple] = {}
        self._pending_encodes: List[tuple] = []  # (xrow, src) queue
        self.metrics.set_gauge(
            "mem/cross_kv_bytes", 2.0 * float(np.prod(shape)) * 4)

    def _cross_cache_vars(self, helper):
        s = self.seq2seq
        shape = [s.n_layers, self.slots + 1, s.kv_heads, s.max_src_len,
                 s.head_dim]
        xk = helper.create_global_variable(name=CROSS_K, shape=shape,
                                           dtype="float32")
        xv = helper.create_global_variable(name=CROSS_V, shape=shape,
                                           dtype="float32")
        return xk, xv

    def _cross_weight_ins(self, helper):
        from ..models.seq2seq import _cross_params

        ins = _cross_params(helper, self.seq2seq.n_layers,
                            self.seq2seq.d_model,
                            self.seq2seq.kv_heads * self.seq2seq.head_dim)
        ins.pop("XKvW")  # encode-time only
        return ins

    # -- program construction ---------------------------------------------
    @property
    def _prefill_feed_names(self):
        return super()._prefill_feed_names + ["serving.xslot",
                                              "serving.src_len"]

    @property
    def _decode_feed_names(self):
        return super()._decode_feed_names + ["serving.xslot",
                                             "serving.src_len"]

    def _sampling_vars(self, rows):
        ins = super()._sampling_vars(rows)
        if rows is None:  # prefill: batch-dim scalars
            xs = data_layer("serving.xslot", shape=[], dtype="int32")
            sl = data_layer("serving.src_len", shape=[], dtype="int32")
        else:
            xs = data_layer("serving.xslot", shape=[rows], dtype="int32",
                            append_batch_size=False)
            sl = data_layer("serving.src_len", shape=[rows],
                            dtype="int32", append_batch_size=False)
        ins["XSlot"] = [xs]
        ins["SrcLen"] = [sl]
        return ins

    def _neutral_sampling_feed(self, rows: int):
        feed = super()._neutral_sampling_feed(rows)
        # vacant rows attend the scrap cross row, one position deep
        feed["serving.xslot"] = np.full(rows, self.slots, np.int32)
        feed["serving.src_len"] = np.ones(rows, np.int32)
        return feed

    def _slot_sampling_feed(self, row, st, feed, step):
        super()._slot_sampling_feed(row, st, feed, step)
        if st.xrow is not None:
            feed["serving.xslot"][row] = st.xrow
            feed["serving.src_len"][row] = self._xrow_len[st.xrow]

    def _build_prefill(self, tc: int):
        prog, startup = Program(), Program()
        with program_guard(prog, startup):
            chunk = data_layer("serving.chunk", shape=[tc], dtype="int64")
            start = data_layer("serving.start", shape=[], dtype="int32")
            length = data_layer("serving.chunk_len", shape=[],
                                dtype="int32")
            table = data_layer("serving.block_table", shape=[self.pmax],
                               dtype="int32")
            helper = LayerHelper("serving_cross_prefill",
                                 main_program=prog,
                                 startup_program=startup)
            ck, cv = self._cache_vars(helper)
            xk, xv = self._cross_cache_vars(helper)
            nxt = helper.block.create_var(
                name="serving.next_tok", shape=[-1],
                dtype="int64", stop_gradient=True)
            ins = {"Chunk": [chunk], "StartPos": [start],
                   "Lengths": [length], "BlockTable": [table],
                   "CacheK": [ck], "CacheV": [cv],
                   "CrossK": [xk], "CrossV": [xv]}
            ins.update(self._sampling_vars(None))
            ins.update(self._lm_ins(helper))
            ins.update(self._cross_weight_ins(helper))
            outs = {"NextTok": [nxt], "CacheK": [ck], "CacheV": [cv]}
            outs.update(self._beam_out_vars(helper, 0, "serving.pf"))
            helper.append_op("transformer_stack_cross_prefill", ins,
                             outs, self._decode_attrs())
        fetches = [nxt.name] + [v[0].name for k, v in sorted(outs.items())
                                if k in ("TopV", "TopI")]
        self._transpile(prog, list(self._prefill_feed_names), fetches,
                        f"transpile/prefill{tc}/")
        return prog, outs

    def _build_decode(self):
        prog, startup = Program(), Program()
        with program_guard(prog, startup):
            tok = data_layer("serving.tok", shape=[self._nslots],
                             dtype="int64", append_batch_size=False)
            pos = data_layer("serving.pos", shape=[self._nslots],
                             dtype="int32", append_batch_size=False)
            table = data_layer("serving.block_table",
                               shape=[self._nslots, self.pmax],
                               dtype="int32", append_batch_size=False)
            helper = LayerHelper("serving_cross_decode",
                                 main_program=prog,
                                 startup_program=startup)
            ck, cv = self._cache_vars(helper)
            xk, xv = self._cross_cache_vars(helper)
            nxt = helper.block.create_var(
                name="serving.next_tok",
                shape=[self._nslots], dtype="int64", stop_gradient=True)
            ins = {"Tok": [tok], "Pos": [pos], "BlockTable": [table],
                   "CacheK": [ck], "CacheV": [cv],
                   "CrossK": [xk], "CrossV": [xv]}
            ins.update(self._sampling_vars(self._nslots))
            ins.update(self._lm_ins(helper))
            ins.update(self._cross_weight_ins(helper))
            outs = {"NextTok": [nxt], "CacheK": [ck], "CacheV": [cv]}
            outs.update(self._beam_out_vars(helper, self._nslots,
                                            "serving.dec"))
            helper.append_op("transformer_stack_cross_decode", ins,
                             outs, self._decode_attrs())
        fetches = [nxt.name] + [v[0].name for k, v in sorted(outs.items())
                                if k in ("TopV", "TopI")]
        self._transpile(prog, list(self._decode_feed_names), fetches,
                        "transpile/decode/")
        return prog, outs

    def _build_encode(self, ts: int):
        from ..models.seq2seq import _cross_params, _encoder_params

        s = self.seq2seq
        prog, startup = Program(), Program()
        with program_guard(prog, startup):
            src = data_layer("serving.src", shape=[ts], dtype="int64")
            n = data_layer("serving.src_n", shape=[], dtype="int32")
            row = data_layer("serving.src_row", shape=[], dtype="int32")
            helper = LayerHelper("serving_encode", main_program=prog,
                                 startup_program=startup)
            xk, xv = self._cross_cache_vars(helper)
            ok = helper.block.create_var(
                name="serving.enc_ok", shape=[-1], dtype="int32",
                stop_gradient=True)
            ins = {"SrcIds": [src], "SrcLen": [n], "SlotIds": [row],
                   "CrossK": [xk], "CrossV": [xv]}
            ins.update(_encoder_params(
                helper, s.src_vocab_size, s.d_model,
                s.d_ff or 4 * s.d_model, s.max_src_len, s.n_layers,
                s.num_heads, s.num_kv_heads))
            ins["XKvW"] = _cross_params(
                helper, s.n_layers, s.d_model,
                s.kv_heads * s.head_dim)["XKvW"]
            helper.append_op(
                "transformer_encdec_encode", ins,
                {"Ok": [ok], "CrossK": [xk], "CrossV": [xv]},
                {"num_heads": s.num_heads,
                 "num_kv_heads": s.num_kv_heads})
        self._transpile(prog, ["serving.src", "serving.src_n",
                               "serving.src_row"], [ok.name],
                        f"transpile/encode{ts}/")
        return prog, ok

    def _encode_prog(self, ts: int):
        if ts not in self._encode_progs:
            self._encode_progs[ts] = self._build_encode(ts)
        return self._encode_progs[ts]

    def _src_bucket_for(self, n: int) -> int:
        for b in self.src_buckets:
            if n <= b:
                return b
        raise BadRequestError(
            f"source length {n} exceeds the largest source bucket "
            f"{self.src_buckets[-1]}")

    # -- admission ---------------------------------------------------------
    def _validate(self, req: Request):
        payload = req.payload
        if not isinstance(payload, dict) or payload.get("src") is None:
            raise BadRequestError(
                "seq2seq request needs {'src': [ids]} (+ optional "
                "'prompt' target prefix)")
        try:
            src = np.asarray(payload["src"], np.int64).reshape(-1)
        except (TypeError, ValueError) as exc:
            raise BadRequestError(f"bad src payload: {exc}")
        if src.size < 1:
            raise BadRequestError("empty src")
        self._src_bucket_for(src.size)  # raises when over-long
        if payload.get("prompt") is None:
            req.payload = dict(payload,
                               prompt=np.asarray([self.bos_id], np.int64))
        parsed = super()._validate(req)
        req.meta["_src"] = src
        return parsed

    def _take_xrow(self, src: np.ndarray) -> int:
        if not self._xrow_free:  # slots >= requests, so rows suffice
            raise RuntimeError("cross-KV rows exhausted (engine bug)")
        row = self._xrow_free.pop()
        self._xrow_ref[row] = 1
        self._xrow_len[row] = src.size
        return row

    def _release_pages(self, st) -> None:
        super()._release_pages(st)
        if getattr(st, "xrow", None) is not None:
            row = st.xrow
            st.xrow = None
            self._xrow_ref[row] -= 1
            if self._xrow_ref[row] == 0:
                self._xrow_free.append(row)

    def _enc_bucket_for(self, n: int) -> int:
        for b in self.encode_batch_buckets:
            if n <= b:
                return b
        return self.encode_batch_buckets[-1]

    def _encode_batch(self, ts: int, items) -> None:
        """One encoder pass for up to a batch bucket of admitted
        sources: transformer_encdec_encode scatters each source's
        cross K/V into its row; padding rows target the scrap row."""
        import time

        from .. import profiler, trace

        nb = self._enc_bucket_for(len(items))
        prog, ok = self._encode_prog(ts)
        feed = {
            "serving.src": np.zeros((nb, ts), np.int64),
            "serving.src_n": np.ones(nb, np.int32),
            "serving.src_row": np.full(nb, self.slots, np.int32),
        }
        for i, (row, src) in enumerate(items):
            feed["serving.src"][i, :src.size] = src
            feed["serving.src_n"][i] = src.size
            feed["serving.src_row"][i] = row
        t0 = time.perf_counter()
        with self._device_ctx(), profiler.timer("serving/encode"), \
                trace.span("serving/encode", batch=len(items),
                           bucket=ts, padded=nb):
            self.executor.run(prog, feed=feed, fetch_list=[ok],
                              scope=self.scope)
        self.metrics.observe_latency(time.perf_counter() - t0,
                                     name="encode")
        self.metrics.inc("encodes", len(items))
        self.metrics.inc("encode_batches")

    def _encode_src(self, row: int, src: np.ndarray) -> None:
        """Encode ONE source immediately (the pre-batching seam, kept
        for direct callers); admission queues into ``_pending_encodes``
        and flushes in buckets instead."""
        self._encode_batch(self._src_bucket_for(src.size), [(row, src)])

    def _flush_encodes(self) -> None:
        """Run every queued encoder pass, grouped by source bucket and
        padded to ``encode_batch_buckets`` — admission stays O(1) and
        the encoder runs at batch efficiency. MUST complete before any
        prefill/decode step attends the new cross rows."""
        if not self._pending_encodes:
            return
        pending, self._pending_encodes = self._pending_encodes, []
        # a request cancelled between admit and flush released its row
        # (possibly re-taken in the same round): keep only the NEWEST
        # pending write per still-referenced row, so the scatter never
        # sees a duplicate or stale SlotId
        live: Dict[int, np.ndarray] = {}
        for row, src in pending:
            if self._xrow_ref[row] > 0:
                live[row] = src
        by_ts: Dict[int, list] = {}
        for row, src in live.items():
            by_ts.setdefault(self._src_bucket_for(src.size),
                             []).append((row, src))
        cap = self.encode_batch_buckets[-1]
        for ts in sorted(by_ts):
            group = by_ts[ts]
            for i in range(0, len(group), cap):
                self._encode_batch(ts, group[i:i + cap])

    def _admit_one(self, req, prompt, max_new, eos, sampling, beam,
                   group) -> str:
        r = super()._admit_one(req, prompt, max_new, eos, sampling, beam,
                               group=group)
        if r != "ok":
            return r
        slot = next(i for i, st in enumerate(self._slots)
                    if st is not None and st.request is req
                    and st.role in ("normal", "beam_parent"))
        src = req.meta["_src"]
        row = self._take_xrow(src)
        self._slots[slot].xrow = row
        self._pending_encodes.append((row, src))
        return r

    # every path into the device that attends cross rows flushes first
    def _run_prefill_group(self, group) -> None:
        self._flush_encodes()
        super()._run_prefill_group(group)

    def prefill_tick(self) -> bool:
        self._flush_encodes()
        return super().prefill_tick()

    def decode_tick(self) -> bool:
        self._flush_encodes()
        return super().decode_tick()

    # -- beam forks share the cross row ------------------------------------
    def _beam_fork(self, src_slot: int, hold_slot: int,
                   n_written: int) -> int:
        slot = super()._beam_fork(src_slot, hold_slot, n_written)
        row = self._slots[src_slot].xrow
        self._slots[slot].xrow = row
        self._xrow_ref[row] += 1
        return slot

    # -- warmup ------------------------------------------------------------
    def warmup(self) -> int:
        combos = super().warmup()
        for ts in self.src_buckets:
            prog, ok = self._encode_prog(ts)
            for nb in self.encode_batch_buckets:
                feed = {"serving.src": np.zeros((nb, ts), np.int64),
                        "serving.src_n": np.ones(nb, np.int32),
                        "serving.src_row": np.full(nb, self.slots,
                                                   np.int32)}
                with self._device_ctx():
                    self.executor.run(prog, feed=feed, fetch_list=[ok],
                                      scope=self.scope)
                combos += 1
        self.metrics.inc("warmup_compiles",
                         len(self.src_buckets)
                         * len(self.encode_batch_buckets))
        return combos

    def _warm_programs(self):
        progs = super()._warm_programs()
        progs.extend(self._encode_prog(ts)[0] for ts in self.src_buckets)
        return progs

    # -- convenience -------------------------------------------------------
    def translate(self, sources: Sequence[Sequence[int]],
                  max_new_tokens: Optional[int] = None,
                  eos_id: Optional[int] = None,
                  sampling=None) -> List[np.ndarray]:
        """Greedy/sampled translation of a source batch; returns
        [bos + generated target ids] per source."""
        from .params import SamplingParams

        max_new = max_new_tokens or self.default_max_new_tokens
        if sampling is None or isinstance(sampling, SamplingParams):
            sampling = [sampling] * len(list(sources))
        reqs = [Request({"src": s},
                        {"max_new_tokens": max_new, "eos_id": eos_id,
                         "sampling_params": sp}, None)
                for s, sp in zip(sources, sampling)]
        self._drive(reqs)
        return [r.future.result(timeout=0.1) for r in reqs]

    def translate_beam(self, src: Sequence[int], beam_size: int = 4,
                       max_new_tokens: Optional[int] = None,
                       eos_id: Optional[int] = None,
                       length_penalty: float = 0.0,
                       return_all: bool = True):
        """Beam-search translation of ONE source sentence: the NMT
        config's fused story — encoder at admission, beams as paged
        forks sharing the source's cross-KV row."""
        req = Request({"src": src},
                      {"max_new_tokens": (max_new_tokens
                                          or self.default_max_new_tokens),
                       "eos_id": eos_id, "beam_size": int(beam_size),
                       "length_penalty": float(length_penalty),
                       "return_beams": bool(return_all)}, None)
        self._drive([req])
        return req.future.result(timeout=0.1)

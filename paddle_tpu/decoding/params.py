"""Per-request decode policy: the one object a request carries.

``SamplingParams`` lifts what used to be engine-wide constructor knobs
(``GenerationEngine(temperature=, top_k=)``) onto the REQUEST, so one
continuous batch freely mixes greedy, temperature-sampled, top-p, and
grammar-masked rows under a single compiled decode step. The engine
holds a *default* SamplingParams (built from the deprecated constructor
args for backward compatibility); request-level fields win field-by-
field (:meth:`SamplingParams.from_meta`).

Determinism contract: a sampled request's tokens are a function of
(request, ``seed``) alone — the engine feeds (seed, step) per row into
the decode computation, so co-batching, tick interleaving, and fleet
hedging never change what a request receives.
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple


def _freeze_stop(stop) -> Tuple[Tuple[int, ...], ...]:
    """Normalize stop input (one sequence or a list of sequences of token
    ids) to a tuple of non-empty int tuples."""
    if stop is None:
        return ()
    seqs = list(stop)
    if seqs and isinstance(seqs[0], (int,)):  # a single flat sequence
        seqs = [seqs]
    out = []
    for s in seqs:
        ids = tuple(int(t) for t in s)
        if not ids:
            raise ValueError("empty stop sequence")
        out.append(ids)
    return tuple(out)


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """One request's decode policy.

    temperature: 0.0 = greedy argmax; > 0 samples the scaled distribution.
    top_k:       keep only the k most likely tokens (0 = off).
    top_p:       nucleus sampling — smallest token set covering this
                 probability mass (1.0 = off).
    seed:        per-request RNG seed. Sampled tokens are reproducible as
                 a function of (request, seed) regardless of batch
                 composition; None lets the engine assign one (and the
                 fleet pins one before hedging, so hedged attempts agree).
    max_tokens:  generation horizon (None = the engine default).
    stop:        token-id sequences that end generation; the matched
                 sequence is NOT included in the returned ids.
    logits_processor: a per-step token-mask hook
                 (:class:`~paddle_tpu.decoding.masks.LogitsProcessor`) —
                 grammar/JSON-schema constrained decoding rides here.
    """

    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: Optional[int] = None
    max_tokens: Optional[int] = None
    stop: Tuple[Tuple[int, ...], ...] = ()
    logits_processor: object = None

    # meta keys a request may carry (the /v1/generate request schema)
    _META_KEYS = ("temperature", "top_k", "top_p", "seed", "stop")

    def __post_init__(self):
        object.__setattr__(self, "stop", _freeze_stop(self.stop))

    def validate(self, vocab_size: Optional[int] = None) -> None:
        if self.temperature < 0:
            raise ValueError(f"temperature must be >= 0, got "
                             f"{self.temperature}")
        if self.top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {self.top_k}")
        if vocab_size is not None and self.top_k > vocab_size:
            raise ValueError(f"top_k {self.top_k} exceeds the vocab "
                             f"({vocab_size})")
        if not 0.0 < self.top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {self.top_p}")
        if self.seed is not None and not (0 <= int(self.seed) < 2 ** 32):
            raise ValueError(f"seed must fit uint32, got {self.seed}")
        if self.max_tokens is not None and self.max_tokens < 1:
            raise ValueError("max_tokens must be >= 1")
        if vocab_size is not None:
            for s in self.stop:
                for t in s:
                    if not 0 <= t < vocab_size:
                        raise ValueError(
                            f"stop token {t} outside the vocab "
                            f"({vocab_size})")

    @property
    def sampled(self) -> bool:
        return self.temperature > 0

    @classmethod
    def from_meta(cls, meta: dict,
                  default: Optional["SamplingParams"] = None
                  ) -> "SamplingParams":
        """Merge request meta over the engine default: any field the
        request carries wins; absent fields inherit the default — the
        composition contract the backward-compat shim pins."""
        default = default or cls()
        meta = meta or {}
        kw = {}
        for key in cls._META_KEYS:
            if meta.get(key) is not None:
                kw[key] = meta[key]
        if meta.get("logits_processor") is not None:
            kw["logits_processor"] = meta["logits_processor"]
        if not kw:
            return default
        return dataclasses.replace(default, **kw)

    def with_seed(self, seed: int) -> "SamplingParams":
        return dataclasses.replace(self, seed=int(seed))


@dataclasses.dataclass(frozen=True)
class BeamParams:
    """Beam-search policy for a request (``beam_size`` in the request
    meta / /v1/generate body). Beam decode is deterministic — sampling
    fields are ignored for beam requests."""

    beam_size: int = 4
    length_penalty: float = 0.0  # GNMT ((5+len)/6)^alpha normalization
    eos_id: Optional[int] = None
    return_all: bool = False     # future result = (ids [K, T], scores [K])

    def validate(self, vocab_size: Optional[int] = None) -> None:
        if self.beam_size < 1:
            raise ValueError(f"beam_size must be >= 1, got "
                             f"{self.beam_size}")
        if vocab_size is not None and self.beam_size > vocab_size:
            raise ValueError(f"beam_size {self.beam_size} exceeds the "
                             f"vocab ({vocab_size})")

    @classmethod
    def from_meta(cls, meta: dict) -> Optional["BeamParams"]:
        k = (meta or {}).get("beam_size")
        if not k or int(k) <= 1:
            return None
        return cls(beam_size=int(k),
                   length_penalty=float(meta.get("length_penalty") or 0.0),
                   eos_id=meta.get("eos_id"),
                   return_all=bool(meta.get("return_beams", False)))

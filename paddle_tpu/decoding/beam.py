"""Beam search as paged-cache forks.

The classical TPU beam search (``transformer_stack_beam_search``) carries
beams on the batch axis and GATHERS every layer's dense cache by parent
index each step — O(K · cache bytes) of HBM traffic per reorder. On the
paged plane a hypothesis fork is bookkeeping instead: duplicate the
parent's int32 block table, bump the refcount on every fully-written
page, and let the engine's existing copy-on-write guard copy the one
partially-written page IF AND WHEN the two hypotheses diverge inside it.
Beams therefore share their entire common prefix in HBM — page growth is
sub-linear in K (pinned by test against the K-dense-copy baseline), and
a "reorder" never moves cache bytes at all.

A :class:`BeamJob` owns one request's hypotheses. The job's slots are
ordinary engine slots: its rows ride the SAME compiled decode step as
every greedy/sampled request in the batch (the op's ``emit_topk`` plane
returns each row's top-K masked log-probs), so beam requests mix freely
with the rest of the continuous batch. Scoring replicates
``transformer_stack_beam_search`` exactly — per-parent top-K candidates
merged by (score desc, parent·V+token asc), frozen (eos) hypotheses
contributing their unchanged score, GNMT ``((5+len)/6)^alpha`` length
normalization at the end — which is what the token-exact-vs-reference
pin checks.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from .params import BeamParams


class _Hyp:
    """One live or frozen hypothesis. ``slot`` is the engine slot whose
    block table holds this hypothesis's cache view (None once frozen —
    a frozen hypothesis needs no more decode work, only its score)."""

    __slots__ = ("slot", "score", "tokens", "alive")

    def __init__(self, slot: Optional[int], score: float,
                 tokens: List[int], alive: bool):
        self.slot = slot
        self.score = float(score)
        self.tokens = tokens
        self.alive = alive


class BeamJob:
    """One beam-search request riding the continuous batch.

    Lifecycle: the engine admits the parent slot normally (prefill /
    prefix hit / chunked streaming all apply) and parks ``K-1`` hold
    slots for the job; the parent's first top-K row expands the initial
    hypotheses (beam 0 keeps the parent's cache, the rest fork); each
    decode tick's top-K rows rerank the beam set — surviving children
    reuse or fork their parent's slot, dead branches release pages back
    to the pool. Pool pressure DEFERS a rerank (the job's slots sit out
    decode ticks until pages free, retried by ``serve_step``) rather
    than failing mid-flight, mirroring the engine's admission-defer
    contract.
    """

    def __init__(self, engine, request, prompt: np.ndarray,
                 max_new: int, params: BeamParams,
                 parent_slot: int, hold_slots: List[int]):
        self.engine = engine
        self.request = request
        self.prompt = np.asarray(prompt, np.int64)
        self.max_new = int(max_new)
        self.params = params
        self.K = int(params.beam_size)
        self.eos_id = -1 if params.eos_id is None else int(params.eos_id)
        self.parent_slot = parent_slot
        self.holds: List[int] = list(hold_slots)
        self.hyps: List[_Hyp] = []
        self.expanded = False
        self.done = False
        # a rerank the pool could not satisfy, retried each tick
        self._pending: Optional[list] = None

    # -- slot inventory ---------------------------------------------------
    @property
    def waiting(self) -> bool:
        return self._pending is not None

    def live_slots(self) -> List[int]:
        return [h.slot for h in self.hyps if h.slot is not None]

    # -- expansion --------------------------------------------------------
    def on_parent_row(self, topv: np.ndarray, topi: np.ndarray) -> None:
        """First top-K row for the parent (from prefill completion, or
        from the first decode tick on a full prefix hit): expand into K
        hypotheses. Beam 0 inherits the parent slot's cache; the others
        fork it (shared written pages, fresh future pages)."""
        eng = self.engine
        n_written = int(self.prompt.size)  # prompt K/V rows on the device
        plan = []  # (token, score, alive)
        for k in range(self.K):
            tok = int(topi[k])
            plan.append((tok, float(topv[k]), self._alive(tok)))
        n_alive = sum(1 for _, _, a in plan if a)
        if not eng._beam_can_fork(self, max(0, n_alive - 1), n_written):
            self._pending = ["expand", np.asarray(topv), np.asarray(topi)]
            eng._beam_park(self)
            return
        self.expanded = True
        self.hyps = []
        parent_used = False
        for tok, score, alive in plan:
            if not alive:
                self.hyps.append(_Hyp(None, score, [tok], False))
                continue
            if not parent_used:
                parent_used = True
                slot = self.parent_slot
            else:
                slot = eng._beam_fork(self.parent_slot, self.holds.pop(),
                                      n_written)
            eng._tok[slot] = tok
            eng._pos[slot] = n_written
            self.hyps.append(_Hyp(slot, score, [tok], True))
        if not parent_used:  # every first token froze: parent unneeded
            eng._beam_release(self.parent_slot, self)
        self._maybe_finish()

    # -- rerank -----------------------------------------------------------
    def on_decode_rows(self, rows: Dict[int, Tuple[np.ndarray, np.ndarray]]
                       ) -> None:
        """One decode tick advanced every alive hypothesis: merge each
        row's top-K continuations with the frozen hypotheses' standing
        scores, keep the global top-K, and reshape the slot set.
        Candidate order replicates the fused reference's
        ``top_k(cand.reshape(K*V))``: score desc, flat parent·V+token
        asc on ties."""
        if self.done or self._pending is not None:
            return
        V = self.engine.spec.vocab_size
        n_before = len(self.hyps[0].tokens)
        cands = []  # (score, flat_index, parent_idx, token)
        for idx, h in enumerate(self.hyps):
            if not h.alive:
                tok = self.eos_id if self.eos_id >= 0 else 0
                cands.append((h.score, idx * V + tok, idx, tok))
                continue
            topv, topi = rows[h.slot]
            for j in range(self.K):
                tok = int(topi[j])
                cands.append((h.score + float(topv[j]), idx * V + tok,
                              idx, tok))
        cands.sort(key=lambda c: (-c[0], c[1]))
        self._apply_rerank(cands[:self.K], n_before)

    def _apply_rerank(self, selected: list, n_before: int) -> None:
        eng = self.engine
        n_written = int(self.prompt.size) + n_before
        # children per parent, in global selection order
        by_parent: Dict[int, List[int]] = {}
        for i, c in enumerate(selected):
            by_parent.setdefault(c[2], []).append(i)
        alive_children = {
            p_idx: sum(1 for i in sel_ids
                       if self.hyps[p_idx].alive
                       and self._alive(selected[i][3]))
            for p_idx, sel_ids in by_parent.items()}
        # 1. dead branches release FIRST: their slots park as holds and
        # their pages free up for the forks below (idempotent across a
        # park/retry — a released parent's slot goes None)
        for idx, h in enumerate(self.hyps):
            if h.slot is not None and not alive_children.get(idx):
                eng._beam_release(h.slot, self)
                h.slot = None
        # 2. feasibility before ANY fork mutates state: park whole or
        # apply whole
        forks = sum(max(0, n - 1) for n in alive_children.values())
        if forks and not eng._beam_can_fork(self, forks, n_written):
            self._pending = ["rerank", selected, n_before]
            eng._beam_park(self)
            return
        # 3. assign: each surviving parent's first alive child inherits
        # its slot, the rest fork it
        new_hyps: List[Optional[_Hyp]] = [None] * len(selected)
        for p_idx, sel_ids in by_parent.items():
            parent = self.hyps[p_idx]
            parent_used = False
            for i in sel_ids:
                score, _flat, _p, tok = selected[i]
                if not parent.alive:  # frozen parent: stays frozen
                    new_hyps[i] = _Hyp(None, score,
                                       parent.tokens + [tok], False)
                    continue
                if not self._alive(tok):  # freezes now
                    new_hyps[i] = _Hyp(None, score,
                                       parent.tokens + [tok], False)
                    continue
                if not parent_used:
                    parent_used = True
                    slot = parent.slot
                else:
                    slot = eng._beam_fork(parent.slot, self.holds.pop(),
                                          n_written)
                eng._tok[slot] = tok
                eng._pos[slot] = n_written
                new_hyps[i] = _Hyp(slot, score, parent.tokens + [tok],
                                   True)
        self.hyps = [h for h in new_hyps if h is not None]
        self._maybe_finish()

    def _alive(self, tok: int) -> bool:
        return (tok != self.eos_id) if self.eos_id >= 0 else True

    def retry(self) -> bool:
        """Re-attempt a pool-deferred expansion/rerank. Returns True when
        the job unblocked (its slots rejoin the decode plane)."""
        if self._pending is None:
            return True
        pending, self._pending = self._pending, None
        if pending[0] == "expand":
            self.on_parent_row(pending[1], pending[2])
        else:
            self._apply_rerank(pending[1], pending[2])
        if self._pending is None:
            self.engine._beam_unpark(self)
            return True
        return False

    # -- completion -------------------------------------------------------
    def _maybe_finish(self) -> None:
        if self._pending is not None or not self.hyps:
            return
        n = len(self.hyps[0].tokens)
        if n >= self.max_new or all(not h.alive for h in self.hyps):
            self._finish()

    def _final_arrays(self):
        """(tokens [K, N], raw scores [K]) padded exactly like the fused
        reference: frozen hypotheses trail eos (0 with no eos)."""
        N = self.max_new
        fill = self.eos_id if self.eos_id >= 0 else 0
        toks = np.full((len(self.hyps), N), fill, np.int64)
        scores = np.zeros(len(self.hyps), np.float64)
        for i, h in enumerate(self.hyps):
            t = np.asarray(h.tokens[:N], np.int64)
            toks[i, :t.size] = t
            scores[i] = h.score
        return toks, scores

    def _finish(self) -> None:
        self.done = True
        toks, scores = self._final_arrays()
        N = self.max_new
        alpha = self.params.length_penalty
        if alpha:
            if self.eos_id >= 0:
                has = (toks == self.eos_id).any(axis=1)
                first = np.argmax(toks == self.eos_id, axis=1) + 1
                gen_len = np.where(has, np.minimum(first, N),
                                   N).astype(np.float64)
            else:
                gen_len = np.full(len(self.hyps), float(N))
            scores = scores / (((5.0 + gen_len) / 6.0) ** alpha)
        order = np.argsort(-scores, kind="stable")
        toks, scores = toks[order], scores[order]
        ids = np.concatenate(
            [np.repeat(self.prompt[None, :], toks.shape[0], axis=0),
             toks], axis=1)
        self.engine._beam_finish(self, ids, scores.astype(np.float32))

"""Per-step logits-processor hook: host-side token masks fed per tick.

A :class:`LogitsProcessor` computes, for each decode step, the set of
token ids the request may emit next. The engine gathers every masked
request's row into ONE [slots, vocab] mask tensor fed into the compiled
decode step — the mask is data, not program, so constrained and
unconstrained requests share the same compile-cache entry and the
steady state stays at zero fresh compiles.

:class:`JsonSchemaMask` is the shipped exemplar: grammar-constrained
decoding of a (restricted) JSON value over a character-level token
mapping. It demonstrates the full pattern — incremental state from the
tokens emitted so far, viable-prefix computation per candidate token —
in a form small enough to read; a production grammar engine plugs into
the same two-method protocol.
"""
from __future__ import annotations

import json
from typing import Dict, Optional, Sequence

import numpy as np


class LogitsProcessor:
    """The per-step token-mask protocol.

    ``mask(step, generated)`` returns a [vocab] float32 vector — 1.0
    where the token is allowed, 0.0 where banned — given the tokens this
    request has emitted so far. Called on the host once per decode tick
    per masked request; the engine feeds the stacked rows into the
    decode computation. A processor must never ban EVERY token (the
    engine substitutes an all-ones row and counts
    ``mask_dead_ends`` if one does).
    """

    vocab_size: int = 0

    def mask(self, step: int, generated: Sequence[int]) -> np.ndarray:
        raise NotImplementedError


class TokenBanMask(LogitsProcessor):
    """Statically ban a token set (the minimal processor — e.g. keep a
    chat model from emitting reserved control ids)."""

    def __init__(self, vocab_size: int, banned: Sequence[int]):
        self.vocab_size = int(vocab_size)
        self._row = np.ones(self.vocab_size, np.float32)
        for t in banned:
            self._row[int(t)] = 0.0

    def mask(self, step: int, generated: Sequence[int]) -> np.ndarray:
        return self._row


class JsonSchemaMask(LogitsProcessor):
    """Constrain generation to JSON matching a (restricted) schema, over
    a character-level vocab map ``{token_id: char}``.

    Supported schemas (enough to demo the hook end to end):
      {"type": "object", "properties": {name: {"type": "integer"|
      "string"}, ...}}  — all properties required, emitted in the
      declared order — plus bare {"type": "integer"} / {"type":
      "string"} / {"type": "array", "items": {"type": "integer"}}.

    Each step recomputes the viable next-character set by checking, for
    every vocab char, whether prefix+char can still extend to a document
    matching the schema; the emitted text therefore parses as valid JSON
    of the right shape BY CONSTRUCTION (pinned by test). Pair with a
    ``stop`` sequence or eos once the document closes.
    """

    def __init__(self, token_chars: Dict[int, str], schema: dict,
                 vocab_size: Optional[int] = None):
        self.token_chars = {int(k): v for k, v in token_chars.items()}
        for tid, ch in self.token_chars.items():
            if len(ch) != 1:
                raise ValueError(
                    f"JsonSchemaMask is character-level: token {tid} maps "
                    f"to {ch!r} (len {len(ch)})")
        self.vocab_size = int(vocab_size if vocab_size is not None
                              else max(self.token_chars) + 1)
        self.schema = schema
        self._grammar = _schema_strings(schema)

    def text_of(self, generated: Sequence[int]) -> str:
        return "".join(self.token_chars.get(int(t), "") for t in generated)

    def complete(self, generated: Sequence[int]) -> bool:
        """Does the emitted text already form a COMPLETE document
        matching the schema? (The engine's stop hook asks this when the
        processor is also the stopping rule.)"""
        return _matches(self._grammar, self.text_of(generated))

    def mask(self, step: int, generated: Sequence[int]) -> np.ndarray:
        prefix = self.text_of(generated)
        row = np.zeros(self.vocab_size, np.float32)
        for tid, ch in self.token_chars.items():
            if _viable(self._grammar, prefix + ch):
                row[tid] = 1.0
        return row


# --------------------------------------------------------------------------
# viable-prefix machinery: the schema compiles to a set of sketch strings
# with digit/char wildcards; a prefix is viable iff it prefixes some
# concrete expansion. Restricted value domains keep this exact and tiny:
# integers are 1-3 digits, strings are 0-4 chars of [a-z].
# --------------------------------------------------------------------------
_DIGITS = "0123456789"
_ALPHA = "abcdefghijklmnopqrstuvwxyz"
_MAX_INT_DIGITS = 3
_MAX_STR_CHARS = 4


def _int_skeletons():
    return ["#" * n for n in range(1, _MAX_INT_DIGITS + 1)]


def _str_skeletons():
    return ['"' + "@" * n + '"' for n in range(_MAX_STR_CHARS + 1)]


def _value_skeletons(schema: dict):
    t = schema.get("type")
    if t == "integer":
        return _int_skeletons()
    if t == "string":
        return _str_skeletons()
    if t == "array":
        item = schema.get("items") or {"type": "integer"}
        inner = _value_skeletons(item)
        outs = ["[]"]
        for n in (1, 2):
            for combo in _combos(inner, n):
                outs.append("[" + ",".join(combo) + "]")
        return outs
    if t == "object":
        props = schema.get("properties") or {}
        parts_per_key = []
        for name, sub in props.items():
            vals = _value_skeletons(sub)
            parts_per_key.append([f'"{name}":{v}' for v in vals])
        outs = []

        def rec(i, acc):
            if i == len(parts_per_key):
                outs.append("{" + ",".join(acc) + "}")
                return
            for p in parts_per_key[i]:
                rec(i + 1, acc + [p])

        rec(0, [])
        return outs or ["{}"]
    raise ValueError(f"unsupported schema {schema!r}")


def _combos(options, n):
    if n == 1:
        return [[o] for o in options]
    return [[o] + rest for o in options for rest in _combos(options, n - 1)]


def _schema_strings(schema: dict):
    return _value_skeletons(schema)


def _char_fits(sk_ch: str, ch: str) -> bool:
    if sk_ch == "#":
        return ch in _DIGITS
    if sk_ch == "@":
        return ch in _ALPHA
    return sk_ch == ch


def _prefix_of(skeleton: str, text: str) -> bool:
    if len(text) > len(skeleton):
        return False
    return all(_char_fits(s, c) for s, c in zip(skeleton, text))


def _viable(skeletons, text: str) -> bool:
    return any(_prefix_of(sk, text) for sk in skeletons)


def _matches(skeletons, text: str) -> bool:
    ok = any(len(sk) == len(text) and _prefix_of(sk, text)
             for sk in skeletons)
    if not ok:
        return False
    try:  # defense in depth: the emitted document must really parse
        json.loads(text)
        return True
    except ValueError:
        return False

"""Batching decorator (reference python/paddle/v2/minibatch.py:18)."""
from __future__ import annotations


def batch(reader, batch_size: int, drop_last: bool = False):
    """Group samples from ``reader`` into lists of ``batch_size``."""

    def batch_reader():
        b = []
        for d in reader():
            b.append(d)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    return batch_reader

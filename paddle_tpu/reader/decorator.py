"""Composable reader decorators.

Port-equivalent of /root/reference/python/paddle/v2/reader/decorator.py:17-236
(map_readers, buffered, shuffle, chain, compose, firstn, xmap_readers,
PipeReader) — pure-Python data plumbing, re-implemented with the same
contracts. A *reader creator* is a zero-arg callable returning an iterable of
samples.
"""
from __future__ import annotations

import itertools
import queue
import random
import subprocess
import threading
import time
from typing import Any, Callable, Iterable, List

__all__ = [
    "map_readers", "buffered", "bucket_by_length", "shuffle", "chain",
    "compose", "firstn", "xmap_readers", "cache", "PipeReader",
    "background_stage", "device_prefetch",
]


class _End:
    """Fill-thread sentinel: normal end of stream."""


class _Error:
    """Fill-thread sentinel: the source raised; re-raise in the consumer."""

    def __init__(self, exc):
        self.exc = exc


def background_stage(source, depth: int, transform: Callable = None):
    """Run ``source()`` (and optionally ``transform`` per item) on a
    background thread, staying up to ``depth`` items ahead of the
    consumer — the generic pipeline stage under ``buffered`` and
    ``device_prefetch``.

    Leak-safe: an abandoned consumer (early ``break``, GC of the
    generator) closes the stage — a stop flag is set and the queue
    drained so a fill thread parked on a full queue always unblocks and
    exits (one blocked inside ``source()`` itself is abandoned after a
    short deadline — closing must never hang on a stalled source);
    source errors propagate to the consumer instead of silently
    truncating the stream.
    """

    def staged():
        q: queue.Queue = queue.Queue(maxsize=max(int(depth), 1))
        stop = threading.Event()

        def fill():
            try:
                for d in source():
                    if stop.is_set():
                        return
                    q.put(transform(d) if transform is not None else d)
                    if stop.is_set():
                        return
                q.put(_End)
            except BaseException as exc:  # noqa: BLE001 - forwarded
                q.put(_Error(exc))

        t = threading.Thread(target=fill, daemon=True)
        t.start()
        try:
            while True:
                e = q.get()
                if e is _End:
                    break
                if isinstance(e, _Error):
                    raise e.exc
                yield e
        finally:
            stop.set()
            # Unblock a fill() parked on a full queue: drain until the
            # thread has observed the stop flag and exited. Bounded: a
            # fill thread blocked inside source() itself (stalled pipe /
            # socket / slow reader) can't be interrupted from here — past
            # the deadline, abandon it (it's a daemon thread) rather than
            # hang the consumer's close/GC path.
            deadline = time.monotonic() + 0.5
            while t.is_alive() and time.monotonic() < deadline:
                try:
                    while True:
                        q.get_nowait()
                except queue.Empty:
                    pass
                t.join(timeout=0.05)

    return staged


def map_readers(func: Callable, *readers):
    """Apply func to the entries read from the given readers, zipped."""

    def reader():
        its = [r() for r in readers]
        for parts in zip(*its):
            yield func(*parts)

    return reader


def shuffle(reader, buf_size: int):
    """Shuffle within a sliding buffer of ``buf_size`` samples."""

    def shuffled():
        buf: List[Any] = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            random.shuffle(buf)
            yield from buf

    return shuffled


def chain(*readers):
    """Concatenate readers back to back."""

    def reader():
        for r in readers:
            yield from r()

    return reader


class ComposeNotAligned(ValueError):
    pass


def compose(*readers, check_alignment: bool = True):
    """Zip readers into combined tuples: (a, (b1, b2)) -> (a, b1, b2)."""

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        its = [r() for r in readers]
        if check_alignment:
            for parts in zip(*its):
                yield sum((make_tuple(p) for p in parts), ())
            # detect ragged tails
            for it in its:
                if next(it, None) is not None:
                    raise ComposeNotAligned("readers have different lengths")
        else:
            for parts in zip(*its):
                yield sum((make_tuple(p) for p in parts), ())

    return reader


def bucket_by_length(reader, batch_size: int, key=None, buf_size: int = 1024,
                     shuffle_buckets: bool = True, seed: int = None,
                     pad_to_multiple: int = None):
    """Batch variable-length samples with like-length neighbours.

    Sorts a sliding ``buf_size`` window by ``key`` (default: len of the
    sample's first column), slices it into batches, and yields the batches
    in shuffled order so length doesn't correlate with training step. On a
    TPU this is the padding-waste lever for the LoD/varlen path: a padded
    batch costs max-length x batch FLOPs, so batching near-equal lengths
    recovers most of what ragged data loses (the reference's RNN benchmark
    relies on the same sorted-bucket trick in its IMDB reader).

    ``pad_to_multiple`` groups by length ROUNDED UP to the multiple (the
    serving engine's bucket-padding trick applied to training): paired
    with ``DataFeeder(pad_to_multiple=m)`` every batch pads to one of a
    handful of bucket lengths instead of its exact max — each distinct
    padded length is a fresh XLA compile signature, so this is what stops
    steady-state varlen training from recompiling.

    Returns a reader of BATCHES (lists of samples), like ``paddle.batch``.
    """
    key = key or (lambda sample: len(sample[0]))
    if pad_to_multiple and pad_to_multiple > 1:
        raw_key, m = key, int(pad_to_multiple)
        key = lambda sample: -(-raw_key(sample) // m) * m  # noqa: E731
    rng = random.Random(seed)

    def bucketed():
        buf: List[Any] = []

        def flush(buf, final):
            buf.sort(key=key)
            n_full = len(buf) // batch_size * batch_size
            batches = [buf[i:i + batch_size]
                       for i in range(0, n_full, batch_size)]
            if shuffle_buckets:
                rng.shuffle(batches)
            yield from batches
            # mid-stream remainders carry into the next window so every
            # batch but (at most) the epoch's last is full-sized — ragged
            # batch shapes would each cost a fresh XLA compile
            if final and n_full < len(buf):
                yield buf[n_full:]
            else:
                buf[:n_full] = []

        for sample in reader():
            buf.append(sample)
            if len(buf) >= buf_size:
                yield from flush(buf, final=False)
        if buf:
            yield from flush(buf, final=True)

    return bucketed


def buffered(reader, size: int):
    """Prefetch up to ``size`` samples on a background thread (the
    DoubleBuffer analogue: reference DataProvider.h:249-271). Built on
    :func:`background_stage`, so abandoning the iterator early leaves no
    live fill thread."""
    return background_stage(reader, depth=size)


def device_prefetch(feed_reader, depth: int = 2, device=None):
    """Overlap host->device transfer with compute: yields feed dicts whose
    arrays are ALREADY device-resident, staying ``depth`` batches ahead on
    a background thread while the executor runs the current step
    (transfers are async; the queue provides the lookahead). The executor
    passes jax.Array feeds through without a host round-trip
    (core/executor.py _normalize_feeds), so this is the TPU-native
    replacement for the reference's double-buffered data providers feeding
    pinned host memory to cudaMemcpyAsync. ``SGD.train(async_depth=N)``
    runs its DataFeeder through this stage so batch stacking never blocks
    dispatch.

    ``feed_reader()`` must yield {name: np.ndarray} dicts (e.g. a
    DataFeeder.feed applied to batches).
    """
    import jax

    def put(feed):
        dev = device or jax.devices()[0]
        return {k: (jax.device_put(v, dev)
                    if not isinstance(v, jax.Array) else v)
                for k, v in feed.items()}

    return background_stage(feed_reader, depth=depth, transform=put)


def firstn(reader, n: int):
    def reader_n():
        return itertools.islice(reader(), n)

    return reader_n


def cache(reader):
    """Materialise a reader once; replay from memory afterwards."""
    all_data: List[Any] = []
    loaded = [False]

    def cached():
        if not loaded[0]:
            for d in reader():
                all_data.append(d)
                yield d
            loaded[0] = True
        else:
            yield from all_data

    return cached


def xmap_readers(mapper: Callable, reader, process_num: int, buffer_size: int,
                 order: bool = False):
    """Parallel map over a reader with ``process_num`` worker threads."""

    def xreader():
        in_q: queue.Queue = queue.Queue(buffer_size)
        out_q: queue.Queue = queue.Queue(buffer_size)
        end = object()

        def feed():
            for i, d in enumerate(reader()):
                in_q.put((i, d))
            for _ in range(process_num):
                in_q.put(end)

        def work():
            while True:
                item = in_q.get()
                if item is end:
                    out_q.put(end)
                    return
                i, d = item
                out_q.put((i, mapper(d)))

        threading.Thread(target=feed, daemon=True).start()
        workers = [threading.Thread(target=work, daemon=True)
                   for _ in range(process_num)]
        for w in workers:
            w.start()
        finished = 0
        pending = {}
        next_idx = 0
        while finished < process_num:
            item = out_q.get()
            if item is end:
                finished += 1
                continue
            i, d = item
            if order:
                pending[i] = d
                while next_idx in pending:
                    yield pending.pop(next_idx)
                    next_idx += 1
            else:
                yield d
        if order:
            for i in sorted(pending):
                yield pending[i]

    return xreader


class PipeReader:
    """Stream samples from a shell command's stdout
    (reference decorator.py PipeReader)."""

    def __init__(self, command: str, bufsize: int = 8192, file_type: str = "plain"):
        self.command = command
        self.bufsize = bufsize
        self.file_type = file_type

    def get_line(self, cut_lines: bool = True, line_break: bytes = b"\n"):
        proc = subprocess.Popen(self.command.split(), bufsize=self.bufsize,
                                stdout=subprocess.PIPE)
        remained = b""
        while True:
            buff = proc.stdout.read(self.bufsize)
            if not buff:
                break
            if cut_lines:
                lines = (remained + buff).split(line_break)
                remained = lines.pop()
                for line in lines:
                    yield line.decode()
            else:
                yield buff
        if remained:
            yield remained.decode()

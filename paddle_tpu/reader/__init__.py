from .decorator import (PipeReader, buffered, cache, chain, compose, firstn,
                        map_readers, shuffle, xmap_readers)
from .minibatch import batch

from .decorator import (PipeReader, background_stage, bucket_by_length,
                        buffered, cache, chain, compose, device_prefetch,
                        firstn, map_readers, shuffle, xmap_readers)
from .minibatch import batch

from .decorator import (PipeReader, bucket_by_length, buffered, cache,
                        chain, compose, firstn, map_readers, shuffle,
                        xmap_readers)
from .minibatch import batch

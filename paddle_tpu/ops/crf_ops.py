"""Linear-chain CRF ops: log-likelihood, viterbi decode, chunk evaluation.

TPU-native replacement for the reference's CRF stack:
- linear_chain_crf_op.{cc,h} — forward alpha recursion + per-sequence
  log-likelihood (the fluid op; CPU-only in the reference)
- crf_decoding_op.{cc,h} — viterbi decode
- legacy CRFLayer / CRFDecodingLayer (gserver/layers/CRFLayer.cpp,
  LinearChainCRF.cpp)
- chunk_eval_op.cc / ChunkEvaluator (gserver/evaluators/ChunkEvaluator.cpp)

The reference walks each sequence with per-row C++ loops over LoD offsets.
Here the alpha/viterbi recursions run as one ``lax.scan`` over the padded
time axis for the whole batch (finished rows carry state through), and the
[tag, tag] transition inner products batch onto the MXU/VPU.

Transition parameter layout matches the reference (linear_chain_crf_op.h):
``Transition`` is [num_tags + 2, num_tags]; row 0 = start weights a_j,
row 1 = end weights b_j, rows 2.. = w_{ij} (from tag i to tag j).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op
from .common import maybe, out, single
from .sequence_ops import time_mask


def _split_transition(trans):
    return trans[0], trans[1], trans[2:]  # start [T], end [T], w [T, T]


@register_op("linear_chain_crf", optional_inputs=("Length",))
def linear_chain_crf(attrs, ins):
    """Negative log-likelihood of tag paths under a linear-chain CRF.

    Inputs: Emission [b, T, n] (unnormalised scores), Transition [n+2, n],
    Label [b, T] int, Length [b]. Outputs LogLikelihood [b, 1] (actually the
    NEGATIVE log-likelihood, matching the reference's sign convention where
    the op output feeds a mean cost), plus Alpha for parity.
    """
    emission = single(ins, "Emission")
    trans = single(ins, "Transition")
    label = single(ins, "Label")
    lengths = maybe(ins, "Length")
    if label.ndim == 3:
        label = label[..., 0]
    b, T, n = emission.shape
    if lengths is None:
        lengths = jnp.full((b,), T, jnp.int32)
    start_w, end_w, w = _split_transition(trans)
    mask = time_mask(lengths, T, emission.dtype)  # [b, T]

    # ---- partition function: alpha recursion in log space -----------------
    em_tm = jnp.swapaxes(emission, 0, 1)  # [T, b, n]
    mask_tm = jnp.swapaxes(mask, 0, 1)  # [T, b]
    alpha0 = start_w[None, :] + em_tm[0]  # [b, n]

    def alpha_step(alpha, xs):
        em_t, m_t = xs
        # logsumexp_i(alpha_i + w_ij) + em_j
        scores = alpha[:, :, None] + w[None, :, :]  # [b, n, n]
        new_alpha = jax.nn.logsumexp(scores, axis=1) + em_t
        alpha = jnp.where(m_t[:, None] > 0, new_alpha, alpha)
        return alpha, alpha

    alpha_last, alphas = jax.lax.scan(alpha_step, alpha0,
                                      (em_tm[1:], mask_tm[1:]))
    log_z = jax.nn.logsumexp(alpha_last + end_w[None, :], axis=-1)  # [b]

    # ---- gold path score --------------------------------------------------
    path_em = jnp.take_along_axis(emission, label[..., None],
                                  axis=2)[..., 0]  # [b, T]
    em_score = jnp.sum(path_em * mask, axis=1)
    trans_pairs = w[label[:, :-1], label[:, 1:]]  # [b, T-1]
    em_score = em_score + jnp.sum(trans_pairs * mask[:, 1:], axis=1)
    first_tag = label[:, 0]
    last_idx = jnp.maximum(lengths - 1, 0)
    last_tag = jnp.take_along_axis(label, last_idx[:, None], axis=1)[:, 0]
    path_score = em_score + start_w[first_tag] + end_w[last_tag]

    nll = (log_z - path_score)[:, None]  # [b, 1]
    alpha_full = jnp.concatenate([alpha0[None], alphas], axis=0)
    return out(LogLikelihood=nll, Alpha=jnp.swapaxes(alpha_full, 0, 1))


@register_op("crf_decoding", optional_inputs=("Length", "Label"))
def crf_decoding(attrs, ins):
    """Viterbi decode (crf_decoding_op.h): best tag path per row.

    Without Label: ViterbiPath [b, T] int64 best tags (padding positions 0).
    With Label (reference behaviour for evaluation): outputs per-position
    0/1 correctness instead.
    """
    emission = single(ins, "Emission")
    trans = single(ins, "Transition")
    lengths = maybe(ins, "Length")
    label = maybe(ins, "Label")
    b, T, n = emission.shape
    if lengths is None:
        lengths = jnp.full((b,), T, jnp.int32)
    start_w, end_w, w = _split_transition(trans)
    mask = time_mask(lengths, T, emission.dtype)
    em_tm = jnp.swapaxes(emission, 0, 1)
    mask_tm = jnp.swapaxes(mask, 0, 1)

    v0 = start_w[None, :] + em_tm[0]  # [b, n]

    def vit_step(v, xs):
        em_t, m_t = xs
        scores = v[:, :, None] + w[None, :, :]  # [b, from, to]
        best_prev = jnp.argmax(scores, axis=1).astype(jnp.int32)  # [b, n]
        new_v = jnp.max(scores, axis=1) + em_t
        v = jnp.where(m_t[:, None] > 0, new_v, v)
        # frozen rows backtrack to "stay" (identity) so padding is harmless
        best_prev = jnp.where(m_t[:, None] > 0, best_prev,
                              jnp.arange(n, dtype=jnp.int32)[None, :])
        return v, best_prev

    v_last, back = jax.lax.scan(vit_step, v0, (em_tm[1:], mask_tm[1:]))
    # back: [T-1, b, n] — back[t][b][j] = best tag at t for tag j at t+1
    final_tag = jnp.argmax(v_last + end_w[None, :], axis=-1).astype(jnp.int32)

    def backtrack(tag, bp_t):
        prev = jnp.take_along_axis(bp_t, tag[:, None], axis=1)[:, 0]
        return prev, tag

    # reverse scan: ys[t] = tag at position t+1, final carry = tag at 0
    first_tag, path_rev = jax.lax.scan(backtrack, final_tag, back,
                                       reverse=True)
    path = jnp.concatenate([first_tag[None], path_rev], axis=0)  # [T, b]
    path = jnp.swapaxes(path, 0, 1) * mask.astype(jnp.int32)  # zero padding
    if label is not None:
        if label.ndim == 3:
            label = label[..., 0]
        correct = (path == label.astype(jnp.int32)).astype(jnp.int64)
        correct = correct * mask.astype(jnp.int64)
        return out(ViterbiPath=correct)
    return out(ViterbiPath=path.astype(jnp.int64))


@register_op("chunk_eval", optional_inputs=("Length",))
def chunk_eval(attrs, ins):
    """Chunk-level precision/recall/F1 (chunk_eval_op.cc, IOB scheme).

    Counts chunks in Inference and Label tag sequences and the matches
    between them. Supports chunk_scheme "IOB" with num_chunk_types k: tag
    2*c = B-type_c, 2*c+1 = I-type_c (the reference's default encoding).
    Outputs Precision/Recall/F1-Score [1] plus raw counts.
    """
    inference = single(ins, "Inference")
    label = single(ins, "Label")
    lengths = maybe(ins, "Length")
    if inference.ndim == 3:
        inference = inference[..., 0]
    if label.ndim == 3:
        label = label[..., 0]
    b, T = label.shape[:2]
    if lengths is None:
        lengths = jnp.full((b,), T, jnp.int32)
    num_types = int(attrs.get("num_chunk_types", 1))
    mask = time_mask(lengths, T, jnp.int32)
    valid = mask > 0

    def chunk_info(tags):
        """IOB starts + membership. Tags 2c=B-c, 2c+1=I-c for c<num_types;
        any tag >= 2*num_types is Outside. A chunk starts at B-c, or at I-c
        when the previous position is not B-c/I-c of the same type."""
        tags = tags.astype(jnp.int32)
        ctype = tags // 2
        in_chunk = (ctype < num_types) & valid
        is_b = (tags % 2) == 0
        prev_t = jnp.pad(ctype, ((0, 0), (1, 0)), constant_values=-1)[:, :-1]
        prev_in = jnp.pad(in_chunk, ((0, 0), (1, 0)),
                          constant_values=False)[:, :-1]
        cont = prev_in & (prev_t == ctype)
        starts = in_chunk & (is_b | ~cont)
        return starts, in_chunk

    inf_starts, inf_in = chunk_info(inference)
    lab_starts, lab_in = chunk_info(label)
    n_inf = jnp.sum(inf_starts)
    n_lab = jnp.sum(lab_starts)

    # A label chunk [s, e] matches an inference chunk iff tags agree on every
    # position of [s, e], chunk starts coincide throughout (so the inference
    # chunk starts at s with no inner boundary), and the inference chunk does
    # not continue past e (at e+1 it must be outside or a fresh start). The
    # continuation check applies only at label-chunk END positions — inner
    # positions are legitimately followed by continuation.
    sagree = inf_starts == lab_starts
    # Matching is by (begin, end, TYPE) — chunk_eval_op.h Segment::operator==
    # — so compare chunk types, not raw B-/I- tags; an I-initiated inference
    # chunk with the right span and type still matches.
    tag_eq = ((inference.astype(jnp.int32) // 2 == label.astype(jnp.int32) // 2)
              & inf_in)
    cont_inf = inf_in & ~inf_starts  # position continues an inference chunk
    cont_lab = lab_in & ~lab_starts
    next_within = (jnp.arange(T)[None, :] + 1) < lengths[:, None]
    cont_inf_next = (jnp.pad(cont_inf, ((0, 0), (0, 1)))[:, 1:]
                     & next_within)
    cont_lab_next = (jnp.pad(cont_lab, ((0, 0), (0, 1)))[:, 1:]
                     & next_within)
    lab_end = lab_in & ~cont_lab_next  # last position of its label chunk
    end_ok = jnp.where(lab_end, ~cont_inf_next, True)
    agree = tag_eq & sagree & end_ok & valid

    # Per-label-chunk segment-min of agreement: segment ids by cumsum of
    # label starts; non-chunk positions go to a dump segment.
    max_chunks = T + 1
    lab_seg = jnp.cumsum(lab_starts.astype(jnp.int32), axis=1)
    flat_seg = lab_seg + jnp.arange(b)[:, None] * max_chunks
    dump = b * max_chunks
    flat_seg = jnp.where(lab_in, flat_seg, dump)
    seg_min = jax.ops.segment_min(
        agree.astype(jnp.int32).reshape(-1), flat_seg.reshape(-1),
        num_segments=dump + 1)
    seg_cnt = jax.ops.segment_sum(
        lab_in.astype(jnp.int32).reshape(-1), flat_seg.reshape(-1),
        num_segments=dump + 1)
    matched = jnp.sum((seg_min[:dump] > 0) & (seg_cnt[:dump] > 0))

    eps = 1e-10
    precision = matched / jnp.maximum(n_inf, 1)
    recall = matched / jnp.maximum(n_lab, 1)
    f1 = 2 * precision * recall / jnp.maximum(precision + recall, eps)
    one = lambda x: jnp.reshape(x.astype(jnp.float32), (1,))
    return {
        "Precision": [one(precision)],
        "Recall": [one(recall)],
        "F1-Score": [one(f1)],
        "NumInferChunks": [jnp.reshape(n_inf.astype(jnp.int64), (1,))],
        "NumLabelChunks": [jnp.reshape(n_lab.astype(jnp.int64), (1,))],
        "NumCorrectChunks": [jnp.reshape(matched.astype(jnp.int64), (1,))],
    }

"""Optimizers-as-ops.

Mirrors the reference's design where each optimizer update is itself an op in
the program (/root/reference/paddle/operators/sgd_op.cc, momentum_op.cc,
adam_op.cc, adamax_op.cc, adagrad_op.cc, decayed_adagrad_op.cc,
adadelta_op.cc, rmsprop_op.cc, ftrl_op.cc, proximal_gd_op.cc,
proximal_adagrad_op.cc; legacy: paddle/parameter/FirstOrderOptimizer.cpp and
the C-ABI lib paddle/optimizer). Because the whole block compiles to one XLA
computation, every parameter's update fuses into the same program as the
backward pass — the TPU equivalent of the reference's fused
TrainingAlgorithmOp kernels — and donated buffers make updates in-place.

All slot names match the reference so program transforms stay portable.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op
from ..core.selected_rows import SelectedRows
from .common import maybe, out, single


def _densify_grad(g):
    """Fallback for optimizers with no row-sparse update rule (matches the
    reference, where only sgd/momentum/adagrad/adam have SelectedRows
    kernels): materialize the dense gradient."""
    return g.to_dense() if isinstance(g, SelectedRows) else g


@register_op("sgd")
def sgd(attrs, ins):
    p = single(ins, "Param")
    g = single(ins, "Grad")
    lr = single(ins, "LearningRate").astype(p.dtype).reshape(())
    if isinstance(g, SelectedRows):
        # Row-sparse update (sgd_op.cc SelectedRows kernel): duplicates in
        # rows accumulate in the scatter-add, so no merge pass is needed.
        return out(ParamOut=p.at[g.rows].add(
            -lr * g.values.astype(p.dtype), mode="drop"))
    return out(ParamOut=p - lr * g.astype(p.dtype))


@register_op("momentum")
def momentum(attrs, ins):
    p = single(ins, "Param")
    g = single(ins, "Grad")
    v = single(ins, "Velocity")
    lr = single(ins, "LearningRate").astype(p.dtype).reshape(())
    mu = attrs.get("mu", 0.9)
    nesterov = attrs.get("use_nesterov", False)
    if isinstance(g, SelectedRows):
        # Lazy momentum: only touched rows' velocity decays this step (the
        # sparse-updater semantics of the reference's legacy sparse
        # momentum, SgdSparseCpuTraining path).
        m = g.merged()
        gv = m.values.astype(p.dtype)
        v_rows = mu * v[m.rows] + gv
        v_out = v.at[m.rows].set(v_rows, mode="drop")
        step = (gv + mu * v_rows) * lr if nesterov else lr * v_rows
        return {"ParamOut": [p.at[m.rows].add(-step, mode="drop")],
                "VelocityOut": [v_out]}
    g = g.astype(p.dtype)
    v_out = mu * v + g
    if nesterov:
        p_out = p - (g + mu * v_out) * lr
    else:
        p_out = p - lr * v_out
    return {"ParamOut": [p_out], "VelocityOut": [v_out]}


@register_op("adam")
def adam(attrs, ins):
    p = single(ins, "Param")
    g = single(ins, "Grad")
    m1 = single(ins, "Moment1")
    m2 = single(ins, "Moment2")
    # keep the STORED beta-pow shape on write-back: emitting a ()-shaped
    # update over the (1,)-declared accumulator would silently retrace
    # the whole step on the second run (and trip the program checker)
    b1p_acc = single(ins, "Beta1Pow")
    b2p_acc = single(ins, "Beta2Pow")
    b1p = b1p_acc.reshape(())
    b2p = b2p_acc.reshape(())
    lr = single(ins, "LearningRate").reshape(())
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    # Decoupled weight decay (AdamW, beyond-reference): p -= lr*wd*p
    # OUTSIDE the moment stream — distinct from L2 regularization, which
    # flows through the gradients (regularizer.py).
    wd = attrs.get("weight_decay", 0.0)
    lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
    if isinstance(g, SelectedRows):
        # Lazy Adam (the reference adam_op's SelectedRows kernel semantics):
        # moments of untouched rows are left alone instead of decaying.
        m = g.merged()
        gv = m.values.astype(jnp.float32)
        m1_rows = b1 * m1[m.rows] + (1 - b1) * gv
        m2_rows = b2 * m2[m.rows] + (1 - b2) * jnp.square(gv)
        step = (lr_t * m1_rows / (jnp.sqrt(m2_rows) + eps)).astype(p.dtype)
        if wd:
            # lazy semantics: decay only the touched rows
            step = step + (lr * wd * p[m.rows]).astype(p.dtype)
        return {
            "ParamOut": [p.at[m.rows].add(-step, mode="drop")],
            "Moment1Out": [m1.at[m.rows].set(m1_rows, mode="drop")],
            "Moment2Out": [m2.at[m.rows].set(m2_rows, mode="drop")],
            "Beta1PowOut": [b1p_acc * b1],
            "Beta2PowOut": [b2p_acc * b2],
        }
    g = g.astype(jnp.float32)
    m1_out = b1 * m1 + (1 - b1) * g
    m2_out = b2 * m2 + (1 - b2) * jnp.square(g)
    p_out = p - (lr_t * m1_out / (jnp.sqrt(m2_out) + eps)).astype(p.dtype)
    if wd:
        p_out = p_out - (lr * wd * p).astype(p.dtype)
    return {
        "ParamOut": [p_out],
        "Moment1Out": [m1_out],
        "Moment2Out": [m2_out],
        "Beta1PowOut": [b1p_acc * b1],
        "Beta2PowOut": [b2p_acc * b2],
    }


@register_op("adamax")
def adamax(attrs, ins):
    p = single(ins, "Param")
    g = _densify_grad(single(ins, "Grad")).astype(jnp.float32)
    m = single(ins, "Moment")
    inf_norm = single(ins, "InfNorm")
    b1p_acc = single(ins, "Beta1Pow")  # keep stored shape on write-back
    b1p = b1p_acc.reshape(())
    lr = single(ins, "LearningRate").reshape(())
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    m_out = b1 * m + (1 - b1) * g
    inf_out = jnp.maximum(b2 * inf_norm, jnp.abs(g))
    lr_t = lr / (1 - b1p)
    p_out = p - (lr_t * m_out / (inf_out + eps)).astype(p.dtype)
    return {"ParamOut": [p_out], "MomentOut": [m_out], "InfNormOut": [inf_out],
            "Beta1PowOut": [b1p_acc * b1]}


@register_op("adagrad")
def adagrad(attrs, ins):
    p = single(ins, "Param")
    g = single(ins, "Grad")
    mom = single(ins, "Moment")
    lr = single(ins, "LearningRate").reshape(())
    eps = attrs.get("epsilon", 1e-6)
    if isinstance(g, SelectedRows):
        # Row-sparse adagrad (adagrad_op.cc SelectedRows kernel).
        m = g.merged()
        gv = m.values.astype(jnp.float32)
        mom_rows = mom[m.rows] + jnp.square(gv)
        step = (lr * gv / (jnp.sqrt(mom_rows) + eps)).astype(p.dtype)
        return {"ParamOut": [p.at[m.rows].add(-step, mode="drop")],
                "MomentOut": [mom.at[m.rows].set(mom_rows, mode="drop")]}
    g = g.astype(jnp.float32)
    mom_out = mom + jnp.square(g)
    p_out = p - (lr * g / (jnp.sqrt(mom_out) + eps)).astype(p.dtype)
    return {"ParamOut": [p_out], "MomentOut": [mom_out]}


@register_op("decayed_adagrad")
def decayed_adagrad(attrs, ins):
    p = single(ins, "Param")
    g = _densify_grad(single(ins, "Grad")).astype(jnp.float32)
    mom = single(ins, "Moment")
    lr = single(ins, "LearningRate").reshape(())
    decay = attrs.get("decay", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    mom_out = decay * mom + (1 - decay) * jnp.square(g)
    p_out = p - (lr * g / (jnp.sqrt(mom_out) + eps)).astype(p.dtype)
    return {"ParamOut": [p_out], "MomentOut": [mom_out]}


@register_op("adadelta")
def adadelta(attrs, ins):
    p = single(ins, "Param")
    g = _densify_grad(single(ins, "Grad")).astype(jnp.float32)
    avg_sq_grad = single(ins, "AvgSquaredGrad")
    avg_sq_upd = single(ins, "AvgSquaredUpdate")
    rho = attrs.get("rho", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    asg_out = rho * avg_sq_grad + (1 - rho) * jnp.square(g)
    update = -jnp.sqrt((avg_sq_upd + eps) / (asg_out + eps)) * g
    asu_out = rho * avg_sq_upd + (1 - rho) * jnp.square(update)
    return {"ParamOut": [p + update.astype(p.dtype)],
            "AvgSquaredGradOut": [asg_out],
            "AvgSquaredUpdateOut": [asu_out]}


@register_op("rmsprop")
def rmsprop(attrs, ins):
    p = single(ins, "Param")
    g = _densify_grad(single(ins, "Grad")).astype(jnp.float32)
    ms = single(ins, "MeanSquare")
    mom = single(ins, "Moment")
    lr = single(ins, "LearningRate").reshape(())
    rho = attrs.get("decay", 0.9)
    eps = attrs.get("epsilon", 1e-10)
    momentum_c = attrs.get("momentum", 0.0)
    ms_out = rho * ms + (1 - rho) * jnp.square(g)
    mom_out = momentum_c * mom + lr * g / jnp.sqrt(ms_out + eps)
    return {"ParamOut": [p - mom_out.astype(p.dtype)],
            "MomentOut": [mom_out], "MeanSquareOut": [ms_out]}


@register_op("ftrl")
def ftrl(attrs, ins):
    p = single(ins, "Param")
    g = _densify_grad(single(ins, "Grad")).astype(jnp.float32)
    sq_acc = single(ins, "SquaredAccumulator")
    lin_acc = single(ins, "LinearAccumulator")
    lr = single(ins, "LearningRate").reshape(())
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    power = attrs.get("lr_power", -0.5)
    new_sq = sq_acc + jnp.square(g)
    sigma = (jnp.power(new_sq, -power) - jnp.power(sq_acc, -power)) / lr
    new_lin = lin_acc + g - sigma * p
    pre = jnp.clip(new_lin, -l1, l1) - new_lin
    denom = jnp.power(new_sq, -power) / lr + 2 * l2
    p_out = (pre / denom).astype(p.dtype)
    return {"ParamOut": [p_out], "SquaredAccumOut": [new_sq],
            "LinearAccumOut": [new_lin]}


@register_op("proximal_gd")
def proximal_gd(attrs, ins):
    p = single(ins, "Param")
    g = _densify_grad(single(ins, "Grad")).astype(jnp.float32)
    lr = single(ins, "LearningRate").reshape(())
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    prox = p - lr * g
    p_out = (jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr * l1, 0.0)
             / (1.0 + lr * l2))
    return out(ParamOut=p_out.astype(p.dtype))


@register_op("proximal_adagrad")
def proximal_adagrad(attrs, ins):
    p = single(ins, "Param")
    g = _densify_grad(single(ins, "Grad")).astype(jnp.float32)
    mom = single(ins, "Moment")
    lr = single(ins, "LearningRate").reshape(())
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    mom_out = mom + jnp.square(g)
    lr_t = lr / jnp.sqrt(mom_out)
    prox = p - lr_t * g
    p_out = (jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr_t * l1, 0.0)
             / (1.0 + lr_t * l2))
    return {"ParamOut": [p_out.astype(p.dtype)], "MomentOut": [mom_out]}


# ---------------------------------------------------------------------------
# Learning-rate schedules as ops
# ---------------------------------------------------------------------------
@register_op("lr_schedule")
def lr_schedule(attrs, ins):
    """Compute the step's learning rate from GlobalStep — one op per policy.

    TPU-native form of the reference's LR schedulers
    (/root/reference/paddle/parameter/LearningRateScheduler.cpp: poly, exp,
    discrete/piecewise, linear policies; fluid drives decay from a
    global_step counter). Computing the LR in-graph keeps the whole
    schedule inside the single compiled train step — no host round-trip
    per step and no recompilation when the LR changes.
    """
    step = single(ins, "GlobalStep").reshape(()).astype(jnp.float32)
    policy = attrs["policy"]
    lr0 = attrs.get("learning_rate", 0.1)
    decay_steps = attrs.get("decay_steps", 1)
    decay_rate = attrs.get("decay_rate", 0.1)
    t = step / decay_steps
    if attrs.get("staircase", False):
        t = jnp.floor(t)
    if policy == "exponential":  # ExpLRS / fluid exponential_decay
        lr = lr0 * jnp.power(decay_rate, t)
    elif policy == "natural_exp":
        lr = lr0 * jnp.exp(-decay_rate * t)
    elif policy == "inverse_time":
        lr = lr0 / (1.0 + decay_rate * t)
    elif policy == "polynomial":  # PolyLRS
        end_lr = attrs.get("end_learning_rate", 1e-4)
        power = attrs.get("power", 1.0)
        if attrs.get("cycle", False):
            ds = decay_steps * jnp.maximum(
                1.0, jnp.ceil(step / decay_steps))
        else:
            ds = jnp.asarray(float(decay_steps), jnp.float32)
        frac = jnp.minimum(step, ds) / ds
        lr = (lr0 - end_lr) * jnp.power(1.0 - frac, power) + end_lr
    elif policy == "piecewise":  # DiscreteExpLRS / ManualLRS-style
        boundaries = jnp.asarray(attrs["boundaries"], jnp.float32)
        values = jnp.asarray(attrs["values"], jnp.float32)
        idx = jnp.sum((step >= boundaries).astype(jnp.int32))
        lr = jnp.take(values, idx)
    elif policy == "noam":  # transformer LR (d_model^-0.5 * min(...))
        warmup = attrs.get("warmup_steps", 4000)
        d_model = attrs.get("d_model", 512)
        s = jnp.maximum(step, 1.0)
        lr = (d_model ** -0.5) * jnp.minimum(s ** -0.5,
                                             s * warmup ** -1.5)
    elif policy == "cosine":  # cosine annealing (modern LM default)
        alpha = attrs.get("alpha", 0.0)
        frac = jnp.minimum(step, float(decay_steps)) / decay_steps
        cos = 0.5 * (1.0 + jnp.cos(jnp.pi * frac))
        lr = lr0 * ((1.0 - alpha) * cos + alpha)
    else:
        raise ValueError(f"unknown lr_schedule policy {policy!r}")
    return out(Out=lr.reshape(1))


@register_op("lr_warmup")
def lr_warmup(attrs, ins):
    """Linear warmup wrapping another LR variable: ramp start->end over
    warmup_steps, then follow the wrapped schedule."""
    lr = single(ins, "LearningRate").reshape(())
    step = single(ins, "GlobalStep").reshape(()).astype(jnp.float32)
    warmup = float(attrs["warmup_steps"])
    start = attrs.get("start_lr", 0.0)
    end = attrs.get("end_lr", 1.0)
    ramp = start + (end - start) * (step / warmup)
    return out(Out=jnp.where(step < warmup, ramp, lr).reshape(1))


@register_op("model_average_update")
def model_average_update(attrs, ins):
    """Windowed parameter-average accumulation (AverageOptimizer,
    /root/reference/paddle/parameter/AverageOptimizer.h; fluid
    optimizer.py ModelAverage): sum_1 accumulates the live parameter each
    step; when the window fills (num_1 >= max_average_window) the buffers
    rotate — sum_2/num_2 take over the history and sum_1 restarts — so the
    average at apply() spans between one and two windows. Purely
    functional where-rotation: no control flow under jit."""
    p = single(ins, "Param")
    s1 = single(ins, "Sum1")
    s2 = single(ins, "Sum2")
    n1 = single(ins, "Num1").reshape(())
    n2 = single(ins, "Num2").reshape(())
    max_w = float(attrs.get("max_average_window", 10000))
    s1n = s1 + p
    n1n = n1 + 1.0
    roll = n1n >= max_w
    return {
        "Sum1Out": [jnp.where(roll, jnp.zeros_like(s1n), s1n)],
        "Sum2Out": [jnp.where(roll, s1n, s2)],
        "Num1Out": [jnp.where(roll, 0.0, n1n).reshape(1)],
        "Num2Out": [jnp.where(roll, n1n, n2).reshape(1)],
    }


@register_op("static_prune_mask")
def static_prune_mask(attrs, ins):
    """Pruning mask from initialized weights (StaticPruningHook,
    /root/reference/paddle/parameter/ParameterUpdaterHook.cpp:39): keep
    the largest-|w| (1 - sparsity_ratio) fraction; the mask is fixed for
    the rest of training and re-applied after every optimizer update."""
    w = single(ins, "Param")
    ratio = float(attrs.get("sparsity_ratio", 0.6))
    flat = jnp.abs(w).reshape(-1)
    n = flat.shape[0]
    keep = max(1, int(round(n * (1.0 - ratio))))
    # mask by sorted INDEX, not by threshold compare: ties at the boundary
    # (e.g. constant-initialized weights) must still prune the exact count,
    # as the reference's index-sorted masking does.
    _, idx = jax.lax.top_k(flat, keep)
    mask = jnp.zeros((n,), w.dtype).at[idx].set(1.0)
    return out(Mask=mask.reshape(w.shape))

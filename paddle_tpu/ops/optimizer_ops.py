"""Optimizers-as-ops.

Mirrors the reference's design where each optimizer update is itself an op in
the program (/root/reference/paddle/operators/sgd_op.cc, momentum_op.cc,
adam_op.cc, adamax_op.cc, adagrad_op.cc, decayed_adagrad_op.cc,
adadelta_op.cc, rmsprop_op.cc, ftrl_op.cc, proximal_gd_op.cc,
proximal_adagrad_op.cc; legacy: paddle/parameter/FirstOrderOptimizer.cpp and
the C-ABI lib paddle/optimizer). Because the whole block compiles to one XLA
computation, every parameter's update fuses into the same program as the
backward pass — the TPU equivalent of the reference's fused
TrainingAlgorithmOp kernels — and donated buffers make updates in-place.

All slot names match the reference so program transforms stay portable.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op
from ..core.selected_rows import SelectedRows
from .common import maybe, out, single


def _densify_grad(g):
    """Fallback for optimizers with no row-sparse update rule (matches the
    reference, where only sgd/momentum/adagrad/adam have SelectedRows
    kernels): materialize the dense gradient."""
    return g.to_dense() if isinstance(g, SelectedRows) else g


@register_op("sgd")
def sgd(attrs, ins):
    p = single(ins, "Param")
    g = single(ins, "Grad")
    lr = single(ins, "LearningRate").astype(p.dtype).reshape(())
    if isinstance(g, SelectedRows):
        # Row-sparse update (sgd_op.cc SelectedRows kernel): duplicates in
        # rows accumulate in the scatter-add, so no merge pass is needed.
        return out(ParamOut=p.at[g.rows].add(
            -lr * g.values.astype(p.dtype), mode="drop"))
    return out(ParamOut=p - lr * g.astype(p.dtype))


@register_op("momentum")
def momentum(attrs, ins):
    p = single(ins, "Param")
    g = single(ins, "Grad")
    v = single(ins, "Velocity")
    lr = single(ins, "LearningRate").astype(p.dtype).reshape(())
    mu = attrs.get("mu", 0.9)
    nesterov = attrs.get("use_nesterov", False)
    if isinstance(g, SelectedRows):
        # Lazy momentum: only touched rows' velocity decays this step (the
        # sparse-updater semantics of the reference's legacy sparse
        # momentum, SgdSparseCpuTraining path).
        m = g.merged()
        gv = m.values.astype(p.dtype)
        v_rows = mu * v[m.rows] + gv
        v_out = v.at[m.rows].set(v_rows, mode="drop")
        step = (gv + mu * v_rows) * lr if nesterov else lr * v_rows
        return {"ParamOut": [p.at[m.rows].add(-step, mode="drop")],
                "VelocityOut": [v_out]}
    g = g.astype(p.dtype)
    v_out = mu * v + g
    if nesterov:
        p_out = p - (g + mu * v_out) * lr
    else:
        p_out = p - lr * v_out
    return {"ParamOut": [p_out], "VelocityOut": [v_out]}


@register_op("adam")
def adam(attrs, ins):
    p = single(ins, "Param")
    g = single(ins, "Grad")
    m1 = single(ins, "Moment1")
    m2 = single(ins, "Moment2")
    b1p = single(ins, "Beta1Pow").reshape(())
    b2p = single(ins, "Beta2Pow").reshape(())
    lr = single(ins, "LearningRate").reshape(())
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
    if isinstance(g, SelectedRows):
        # Lazy Adam (the reference adam_op's SelectedRows kernel semantics):
        # moments of untouched rows are left alone instead of decaying.
        m = g.merged()
        gv = m.values.astype(jnp.float32)
        m1_rows = b1 * m1[m.rows] + (1 - b1) * gv
        m2_rows = b2 * m2[m.rows] + (1 - b2) * jnp.square(gv)
        step = (lr_t * m1_rows / (jnp.sqrt(m2_rows) + eps)).astype(p.dtype)
        return {
            "ParamOut": [p.at[m.rows].add(-step, mode="drop")],
            "Moment1Out": [m1.at[m.rows].set(m1_rows, mode="drop")],
            "Moment2Out": [m2.at[m.rows].set(m2_rows, mode="drop")],
            "Beta1PowOut": [b1p * b1],
            "Beta2PowOut": [b2p * b2],
        }
    g = g.astype(jnp.float32)
    m1_out = b1 * m1 + (1 - b1) * g
    m2_out = b2 * m2 + (1 - b2) * jnp.square(g)
    p_out = p - (lr_t * m1_out / (jnp.sqrt(m2_out) + eps)).astype(p.dtype)
    return {
        "ParamOut": [p_out],
        "Moment1Out": [m1_out],
        "Moment2Out": [m2_out],
        "Beta1PowOut": [b1p * b1],
        "Beta2PowOut": [b2p * b2],
    }


@register_op("adamax")
def adamax(attrs, ins):
    p = single(ins, "Param")
    g = _densify_grad(single(ins, "Grad")).astype(jnp.float32)
    m = single(ins, "Moment")
    inf_norm = single(ins, "InfNorm")
    b1p = single(ins, "Beta1Pow").reshape(())
    lr = single(ins, "LearningRate").reshape(())
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    m_out = b1 * m + (1 - b1) * g
    inf_out = jnp.maximum(b2 * inf_norm, jnp.abs(g))
    lr_t = lr / (1 - b1p)
    p_out = p - (lr_t * m_out / (inf_out + eps)).astype(p.dtype)
    return {"ParamOut": [p_out], "MomentOut": [m_out], "InfNormOut": [inf_out],
            "Beta1PowOut": [b1p * b1]}


@register_op("adagrad")
def adagrad(attrs, ins):
    p = single(ins, "Param")
    g = single(ins, "Grad")
    mom = single(ins, "Moment")
    lr = single(ins, "LearningRate").reshape(())
    eps = attrs.get("epsilon", 1e-6)
    if isinstance(g, SelectedRows):
        # Row-sparse adagrad (adagrad_op.cc SelectedRows kernel).
        m = g.merged()
        gv = m.values.astype(jnp.float32)
        mom_rows = mom[m.rows] + jnp.square(gv)
        step = (lr * gv / (jnp.sqrt(mom_rows) + eps)).astype(p.dtype)
        return {"ParamOut": [p.at[m.rows].add(-step, mode="drop")],
                "MomentOut": [mom.at[m.rows].set(mom_rows, mode="drop")]}
    g = g.astype(jnp.float32)
    mom_out = mom + jnp.square(g)
    p_out = p - (lr * g / (jnp.sqrt(mom_out) + eps)).astype(p.dtype)
    return {"ParamOut": [p_out], "MomentOut": [mom_out]}


@register_op("decayed_adagrad")
def decayed_adagrad(attrs, ins):
    p = single(ins, "Param")
    g = _densify_grad(single(ins, "Grad")).astype(jnp.float32)
    mom = single(ins, "Moment")
    lr = single(ins, "LearningRate").reshape(())
    decay = attrs.get("decay", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    mom_out = decay * mom + (1 - decay) * jnp.square(g)
    p_out = p - (lr * g / (jnp.sqrt(mom_out) + eps)).astype(p.dtype)
    return {"ParamOut": [p_out], "MomentOut": [mom_out]}


@register_op("adadelta")
def adadelta(attrs, ins):
    p = single(ins, "Param")
    g = _densify_grad(single(ins, "Grad")).astype(jnp.float32)
    avg_sq_grad = single(ins, "AvgSquaredGrad")
    avg_sq_upd = single(ins, "AvgSquaredUpdate")
    rho = attrs.get("rho", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    asg_out = rho * avg_sq_grad + (1 - rho) * jnp.square(g)
    update = -jnp.sqrt((avg_sq_upd + eps) / (asg_out + eps)) * g
    asu_out = rho * avg_sq_upd + (1 - rho) * jnp.square(update)
    return {"ParamOut": [p + update.astype(p.dtype)],
            "AvgSquaredGradOut": [asg_out],
            "AvgSquaredUpdateOut": [asu_out]}


@register_op("rmsprop")
def rmsprop(attrs, ins):
    p = single(ins, "Param")
    g = _densify_grad(single(ins, "Grad")).astype(jnp.float32)
    ms = single(ins, "MeanSquare")
    mom = single(ins, "Moment")
    lr = single(ins, "LearningRate").reshape(())
    rho = attrs.get("decay", 0.9)
    eps = attrs.get("epsilon", 1e-10)
    momentum_c = attrs.get("momentum", 0.0)
    ms_out = rho * ms + (1 - rho) * jnp.square(g)
    mom_out = momentum_c * mom + lr * g / jnp.sqrt(ms_out + eps)
    return {"ParamOut": [p - mom_out.astype(p.dtype)],
            "MomentOut": [mom_out], "MeanSquareOut": [ms_out]}


@register_op("ftrl")
def ftrl(attrs, ins):
    p = single(ins, "Param")
    g = _densify_grad(single(ins, "Grad")).astype(jnp.float32)
    sq_acc = single(ins, "SquaredAccumulator")
    lin_acc = single(ins, "LinearAccumulator")
    lr = single(ins, "LearningRate").reshape(())
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    power = attrs.get("lr_power", -0.5)
    new_sq = sq_acc + jnp.square(g)
    sigma = (jnp.power(new_sq, -power) - jnp.power(sq_acc, -power)) / lr
    new_lin = lin_acc + g - sigma * p
    pre = jnp.clip(new_lin, -l1, l1) - new_lin
    denom = jnp.power(new_sq, -power) / lr + 2 * l2
    p_out = (pre / denom).astype(p.dtype)
    return {"ParamOut": [p_out], "SquaredAccumOut": [new_sq],
            "LinearAccumOut": [new_lin]}


@register_op("proximal_gd")
def proximal_gd(attrs, ins):
    p = single(ins, "Param")
    g = _densify_grad(single(ins, "Grad")).astype(jnp.float32)
    lr = single(ins, "LearningRate").reshape(())
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    prox = p - lr * g
    p_out = (jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr * l1, 0.0)
             / (1.0 + lr * l2))
    return out(ParamOut=p_out.astype(p.dtype))


@register_op("proximal_adagrad")
def proximal_adagrad(attrs, ins):
    p = single(ins, "Param")
    g = _densify_grad(single(ins, "Grad")).astype(jnp.float32)
    mom = single(ins, "Moment")
    lr = single(ins, "LearningRate").reshape(())
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    mom_out = mom + jnp.square(g)
    lr_t = lr / jnp.sqrt(mom_out)
    prox = p - lr_t * g
    p_out = (jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr_t * l1, 0.0)
             / (1.0 + lr_t * l2))
    return {"ParamOut": [p_out.astype(p.dtype)], "MomentOut": [mom_out]}

"""Shared helpers for op kernels."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# ---------------------------------------------------------------------------
# MXU precision policy for matmul/conv ops.
#
# None (the default) lets XLA use the MXU fast path: bf16 multiplies with f32
# accumulation — the TPU-native training tradeoff. "highest" forces multi-pass
# f32-exact contraction (~6x slower on the MXU); the checkgrad job and
# tight-tolerance numeric tests switch to it, mirroring the reference's
# --job=checkgrad mode (/root/reference/paddle/trainer/TrainerMain.cpp:54).
# ---------------------------------------------------------------------------
# Tri-state: _UNSET defers to --mxu_precision / --use_amp (flags.py) so a
# flag flip (env var, parse_flags, set_flags) takes effect immediately;
# an explicit set_mxu_precision()/set_amp() call wins over the flag.
_UNSET = object()
_MXU_PRECISION = _UNSET


def _precision_table():
    import jax

    return {
        None: None, "default": None,
        "high": jax.lax.Precision.HIGH,
        "highest": jax.lax.Precision.HIGHEST,
    }


def set_mxu_precision(p):
    """Set contraction precision globally: None/'default' | 'high' | 'highest'."""
    global _MXU_PRECISION
    _MXU_PRECISION = _precision_table()[p]


def mxu_precision(*_arrays):
    if _MXU_PRECISION is _UNSET:
        from ..flags import FLAGS

        return _precision_table()[FLAGS.mxu_precision]
    return _MXU_PRECISION


# ---------------------------------------------------------------------------
# Mixed-precision (AMP) policy: bf16 compute with f32 master weights.
#
# When enabled, matmul/conv kernels cast f32 operands to bf16 on entry and
# emit bf16 activations, halving HBM traffic and using the MXU's native input
# width; accumulation stays f32 (preferred_element_type) and parameters in
# the scope stay f32 — gradients flow back through the casts and arrive f32
# at the optimizer ops (master-weight training). Loss/normalisation ops
# compute their reductions in f32. The reference's float16 support
# (/root/reference/paddle/math/float16.h) never reached its training path;
# on TPU bf16 is the idiomatic default for the hot ops.
# ---------------------------------------------------------------------------
_AMP = _UNSET


def set_amp(enabled: bool):
    global _AMP
    _AMP = bool(enabled)


def amp_enabled() -> bool:
    if _AMP is _UNSET:
        from ..flags import FLAGS

        return FLAGS.use_amp
    return _AMP


def amp_cast(*arrays):
    """Under AMP, cast f32 arrays to bf16 (others pass through)."""
    if not amp_enabled():
        return arrays if len(arrays) > 1 else arrays[0]
    cast = tuple(a.astype(jnp.bfloat16) if a.dtype == jnp.float32 else a
                 for a in arrays)
    return cast if len(cast) > 1 else cast[0]


def single(ins, slot):
    """Fetch the single array bound to ``slot`` (errors if absent)."""
    return ins[slot][0]


def maybe(ins, slot):
    vals = ins.get(slot)
    return vals[0] if vals else None


def out(**kw):
    """Build an output dict: out(Out=x) -> {"Out": [x]}; lists pass through."""
    return {k: (v if isinstance(v, list) else [v]) for k, v in kw.items()}


def broadcast_to_x(x, y, axis: int = -1):
    """Reference elementwise broadcast semantics (elementwise_op.h):

    ``y``'s shape must match a contiguous run of ``x``'s dims starting at
    ``axis`` (axis=-1 means trailing-aligned, i.e. standard numpy rules).
    Returns y reshaped so jnp broadcasting against x is valid.
    """
    if x.ndim == y.ndim or y.ndim == 0:
        return y
    if axis == -1:
        axis = x.ndim - y.ndim
    new_shape = (1,) * axis + y.shape + (1,) * (x.ndim - axis - y.ndim)
    return y.reshape(new_shape)


def normalize_pair(v, n=2):
    """int -> [v]*n ; list passes through."""
    if isinstance(v, (int, np.integer)):
        return [int(v)] * n
    return [int(x) for x in v]

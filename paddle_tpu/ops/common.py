"""Shared helpers for op kernels."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def single(ins, slot):
    """Fetch the single array bound to ``slot`` (errors if absent)."""
    return ins[slot][0]


def maybe(ins, slot):
    vals = ins.get(slot)
    return vals[0] if vals else None


def out(**kw):
    """Build an output dict: out(Out=x) -> {"Out": [x]}; lists pass through."""
    return {k: (v if isinstance(v, list) else [v]) for k, v in kw.items()}


def broadcast_to_x(x, y, axis: int = -1):
    """Reference elementwise broadcast semantics (elementwise_op.h):

    ``y``'s shape must match a contiguous run of ``x``'s dims starting at
    ``axis`` (axis=-1 means trailing-aligned, i.e. standard numpy rules).
    Returns y reshaped so jnp broadcasting against x is valid.
    """
    if x.ndim == y.ndim or y.ndim == 0:
        return y
    if axis == -1:
        axis = x.ndim - y.ndim
    new_shape = (1,) * axis + y.shape + (1,) * (x.ndim - axis - y.ndim)
    return y.reshape(new_shape)


def normalize_pair(v, n=2):
    """int -> [v]*n ; list passes through."""
    if isinstance(v, (int, np.integer)):
        return [int(v)] * n
    return [int(x) for x in v]

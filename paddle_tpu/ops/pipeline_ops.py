"""Pipelined transformer stack op: L pre-LN blocks with stacked weights.

The layer stack carries every weight with a leading layer axis [L, ...],
which buys two TPU-native wins at once: a single ``lax.scan`` over layers
(one compiled block body instead of L inlined copies — the XLA compile-time
idiom for deep stacks), and pipeline parallelism for free — when the
executor mesh has a ``pp`` axis the same stacked tensors shard their layer
axis across stages and run under the GPipe schedule
(parallel/pipeline.gpipe). The reference's closest machinery places whole
layer ranges on devices by config and moves activations by memcpy
(/root/reference/paddle/gserver/gradientmachines/ParallelNeuralNetwork.cpp);
here placement is a sharding spec and movement is an ICI ppermute.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op
from ..kernels.flash_attention import flash_attention, rotary
from .common import amp_cast, maybe, mxu_precision, out, single

_EPS = 1e-5


def _ln(x, scale, bias):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + _EPS) * scale + bias


def _block(p, x, num_heads, causal, num_kv_heads=None, use_rope=False):
    """One pre-LN transformer block; p holds per-layer (no leading dim)
    weights: ln1_s, ln1_b, qkv_w, out_w, ln2_s, ln2_b, ff_w1, ff_b1,
    ff_w2, ff_b2."""
    b, T, d = x.shape
    from jax.ad_checkpoint import checkpoint_name

    q, k, v = _attn_proj(p, x, num_heads, num_kv_heads, use_rope)
    k, v = _expand_kv(k, v, num_heads)
    ctx = flash_attention(q, k, v, causal=causal)
    ctx = checkpoint_name(ctx.transpose(0, 2, 1, 3).reshape(b, T, d),
                          "attn_ctx")
    return _attn_out_ffn(p, x, ctx)


def _attn_proj(p, h, num_heads, num_kv_heads=None, use_rope=False,
               pos0=0):
    """LN1 + qkv projection -> q [b, H, t, dh], k/v [b, Hkv, t, dh].
    Hkv < H is grouped-query attention: the stacked qkv weight is
    [L, d, d + 2*Hkv*dh] and the KV planes (and decode caches) shrink by
    H/Hkv. ``use_rope`` rotates q/k at absolute positions pos0..pos0+t-1
    (rotated keys enter the decode cache, so cached rows never re-rotate)."""
    num_kv_heads = num_kv_heads or num_heads
    b, t, d = h.shape
    head_d = d // num_heads
    d_kv = head_d * num_kv_heads
    from jax.ad_checkpoint import checkpoint_name

    hn = _ln(h, p["ln1_s"], p["ln1_b"])
    hn_c, qkv_c = amp_cast(hn, p["qkv_w"])
    qkv = jnp.einsum("btd,de->bte", hn_c, qkv_c,
                     precision=mxu_precision()).astype(h.dtype)
    qkv = checkpoint_name(qkv, "qkv_proj")
    q = qkv[..., :d]
    k = qkv[..., d:d + d_kv]
    v = qkv[..., d + d_kv:]

    def heads(a, n):
        return a.reshape(b, t, n, head_d).transpose(0, 2, 1, 3)

    q, k, v = (heads(q, num_heads), heads(k, num_kv_heads),
               heads(v, num_kv_heads))
    if use_rope:
        q = rotary(q, pos0)
        k = rotary(k, pos0)
    return q, k, v


def _expand_kv(k, v, num_heads):
    """Broadcast Hkv heads to their H/Hkv query groups."""
    rep = num_heads // k.shape[1]
    if rep > 1:
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    return k, v


def _attn_out_ffn(p, x, ctx):
    """Out-projection + residual + FFN half of a block; ctx [b, t, d]."""
    from jax.ad_checkpoint import checkpoint_name

    ctx_c, ow_c = amp_cast(ctx, p["out_w"])
    attn = jnp.einsum("btd,de->bte", ctx_c, ow_c,
                      precision=mxu_precision()).astype(x.dtype)
    attn = checkpoint_name(attn, "attn_out")
    x = x + attn
    h2 = _ln(x, p["ln2_s"], p["ln2_b"])
    h2_c, w1_c = amp_cast(h2, p["ff_w1"])
    ff = jax.nn.gelu(
        jnp.einsum("btd,df->btf", h2_c, w1_c,
                   precision=mxu_precision()).astype(x.dtype) + p["ff_b1"])
    ff = checkpoint_name(ff, "ffn_hidden")
    ff_c, w2_c = amp_cast(ff, p["ff_w2"])
    ff = jnp.einsum("btf,fd->btd", ff_c, w2_c,
                    precision=mxu_precision()).astype(x.dtype) + p["ff_b2"]
    return x + ff


_STACK_SLOTS = {
    "Ln1S": "ln1_s", "Ln1B": "ln1_b", "QkvW": "qkv_w", "OutW": "out_w",
    "Ln2S": "ln2_s", "Ln2B": "ln2_b", "FfW1": "ff_w1", "FfB1": "ff_b1",
    "FfW2": "ff_w2", "FfB2": "ff_b2",
}


@register_op("pipelined_transformer_stack")
def pipelined_transformer_stack(attrs, ins):
    """X [b, T, d] + stacked block weights (leading dim L) -> Out [b, T, d].

    attrs: num_heads, causal, n_microbatches. With a ``pp`` mesh axis the
    stack runs the GPipe schedule (layer axis sharded into stages, each
    stage scanning its local L/S layers); otherwise one scan over all L.
    """
    from ..parallel.context import current_mesh, mesh_axis

    x = single(ins, "X")
    params = {key: single(ins, slot)
              for slot, key in _STACK_SLOTS.items()}
    num_heads = attrs["num_heads"]
    num_kv_heads = attrs.get("num_kv_heads") or num_heads
    use_rope = attrs.get("use_rope", False)
    causal = attrs.get("causal", True)

    remat = attrs.get("remat", False)

    def scan_layers(p, h):
        def body(carry, layer_p):
            return _block(layer_p, carry, num_heads, causal,
                          num_kv_heads, use_rope), None

        if remat == "dots":
            # Selective policy: keep each layer's big GEMM outputs
            # (qkv/attn-out/ctx/ffn-hidden) resident and recompute only
            # the cheap elementwise/LN work in the backward — the
            # all-or-nothing form re-runs every forward matmul per layer
            # (measured 30.0% vs 48.1% per-layer MFU at d1024, PERF.md).
            body = jax.checkpoint(
                body, policy=jax.checkpoint_policies.save_only_these_names(
                    "qkv_proj", "attn_ctx", "attn_out", "ffn_hidden"))
        elif remat:
            body = jax.checkpoint(body)
        h, _ = jax.lax.scan(body, h, p)
        return h

    pipe_axis = attrs.get("pipe_axis") or "pp"
    pp = mesh_axis(pipe_axis)
    L = params["qkv_w"].shape[0]
    if pp > 1:
        from ..parallel.pipeline import gpipe

        if L % pp:
            raise ValueError(
                f"{L} layers not divisible by pipeline size {pp}")
        mesh = current_mesh()
        data_axis = attrs.get("data_axis") or "dp"
        if data_axis not in mesh.axis_names:
            data_axis = None
        y = gpipe(scan_layers, params, x, mesh, axis=pipe_axis,
                  n_microbatches=attrs.get("n_microbatches") or pp,
                  data_axis=data_axis)
        return out(Out=y)
    return out(Out=scan_layers(params, x))



def _unpack_lm_ins(ins):
    """Shared input unpacking for the decode ops: (prompt, embeddings,
    final-LN, head, stacked block params). PosEmb is absent under RoPE
    (rotation replaces the learned table)."""
    return (single(ins, "Prompt"), single(ins, "TokEmb"),
            maybe(ins, "PosEmb"), single(ins, "FinalLnS"),
            single(ins, "FinalLnB"), single(ins, "HeadW"),
            {key: single(ins, slot) for slot, key in _STACK_SLOTS.items()})


def _embed_fn(tok_emb, pos_emb):
    def embed(ids, pos0):
        if pos_emb is None:  # RoPE: positions live in the attention rotation
            return tok_emb[ids]
        t = ids.shape[1]
        return (tok_emb[ids]
                + jax.lax.dynamic_slice_in_dim(pos_emb, pos0, t, 0)[None])

    return embed


def _logits_fn(ln_s, ln_b, head_w):
    def logits_of(h_last):
        hn = _ln(h_last, ln_s, ln_b)
        hn_c, hw_c = amp_cast(hn, head_w)
        return jnp.einsum("bd,dv->bv", hn_c, hw_c,
                          precision=mxu_precision()).astype(jnp.float32)

    return logits_of


def _prefill(params, x, num_heads, b, Tp, num_kv_heads=None,
             use_rope=False):
    """Run the stack over the prompt capturing every layer's K/V:
    returns (hidden [b, Tp, d], ks, vs [L, b, Hkv, Tp, dh]) — the caches
    hold KV heads only (the GQA memory win). Under RoPE the cached keys
    are already rotated at their absolute positions."""
    def prefill_body(h, layer_p):
        q, k, v = _attn_proj(layer_p, h, num_heads, num_kv_heads,
                             use_rope)
        kx, vx = _expand_kv(k, v, num_heads)
        ctx = flash_attention(q, kx, vx, causal=True)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(b, Tp, x.shape[-1])
        return _attn_out_ffn(layer_p, h, ctx), (k, v)

    return jax.lax.scan(prefill_body, x, params)


def _decode_layer_fn(params, num_heads, d, num_kv_heads=None,
                     use_rope=False):
    """One-token decode through all layers against the cache; returns a
    fn(h1, (layer_p, ck_l, cv_l), pos) suitable for lax.scan over layers
    (pos = the query's position; cache rows < pos+1 are visible). Caches
    store Hkv heads; queries expand to their groups at attention time."""
    from ..kernels.flash_attention import reference_attention

    def layer(h1, inp, pos):
        layer_p, ck_l, cv_l = inp
        q, k, v = _attn_proj(layer_p, h1, num_heads, num_kv_heads,
                             use_rope, pos0=pos)
        ck_l = jax.lax.dynamic_update_slice_in_dim(ck_l, k, pos, 2)
        cv_l = jax.lax.dynamic_update_slice_in_dim(cv_l, v, pos, 2)
        # reference_attention reads the Hkv cache natively (grouped
        # einsum) — no [b, H, T, dh] expansion on the decode hot path
        ctx = reference_attention(
            q, ck_l, cv_l, lengths=jnp.full((h1.shape[0],), pos + 1))
        ctx = ctx.transpose(0, 2, 1, 3).reshape(h1.shape[0], 1, d)
        return _attn_out_ffn(layer_p, h1, ctx), (ck_l, cv_l)

    return layer


def _make_pick(temperature, top_k, vocab, rng):
    """Next-token selection shared by the decode ops: argmax when
    ``temperature`` == 0 (draws nothing — the op's needs_rng predicate
    keeps the scope RNG untouched), otherwise temperature/top-k sampling
    folding ``step`` into the rng so every call draws fresh."""
    if top_k and not 0 < top_k <= vocab:
        raise ValueError(f"top_k {top_k} outside [1, vocab {vocab}]")

    def pick(logits, step):
        if temperature == 0.0:
            return jnp.argmax(logits, axis=-1)
        z = logits
        if top_k:
            kth = jax.lax.top_k(z, top_k)[0][:, -1:]
            z = jnp.where(z >= kth, z, -jnp.inf)
        return jax.random.categorical(jax.random.fold_in(rng, step),
                                      z / temperature, axis=-1)

    return pick


@register_op("transformer_stack_generate", optional_inputs=("PosEmb",),
             needs_rng=lambda attrs: (attrs.get("temperature") or 0) > 0)
def transformer_stack_generate(attrs, ins, rng):
    """Incremental decoding with a per-layer KV cache.

    Prompt [b, Tp] int + the stacked block weights + TokEmb [V, d],
    PosEmb [maxlen, d], FinalLnS/FinalLnB [d], HeadW [d, V]
    -> Out [b, Tp + max_new_tokens] int.

    The serving path the training stack earns: prefill runs the blocks
    once over the prompt while capturing every layer's K/V; the decode
    loop is a lax.scan over steps — one token embeds, attends against the
    cache (position-masked), appends its K/V, and the next id comes from
    argmax (temperature attr == 0) or temperature/top-k sampling through
    the executor's RNG plane. O(T) work per token instead of O(T^2)
    re-forwarding; everything static-shaped for XLA (the cache is
    preallocated at Tp + N).
    """
    (prompt, tok_emb, pos_emb, ln_s, ln_b, head_w,
     params) = _unpack_lm_ins(ins)
    num_heads = attrs["num_heads"]
    num_kv_heads = attrs.get("num_kv_heads") or num_heads
    use_rope = attrs.get("use_rope", False)
    N = attrs["max_new_tokens"]
    temperature = attrs.get("temperature") or 0.0
    top_k = attrs.get("top_k") or 0
    b, Tp = prompt.shape
    L, d = params["ln1_s"].shape
    Ttot = Tp + N
    if pos_emb is not None and Ttot > pos_emb.shape[0]:
        raise ValueError(
            f"prompt {Tp} + {N} new tokens exceeds max_len "
            f"{pos_emb.shape[0]}")
    embed = _embed_fn(tok_emb, pos_emb)
    logits_of = _logits_fn(ln_s, ln_b, head_w)
    vocab = head_w.shape[1]
    pick = _make_pick(temperature, top_k, vocab, rng)

    # ---- prefill: run the stack over the prompt, capturing K/V -------
    h, (ks, vs) = _prefill(params, embed(prompt, 0), num_heads, b, Tp,
                           num_kv_heads, use_rope)
    pad = [(0, 0)] * 5
    pad[3] = (0, N)  # [L, b, Hkv, Tp, dh] -> [L, b, Hkv, Ttot, dh]
    cache_k = jnp.pad(ks, pad)
    cache_v = jnp.pad(vs, pad)
    next_tok = pick(logits_of(h[:, -1]), 0)  # [b]
    decode_layer = _decode_layer_fn(params, num_heads, d, num_kv_heads,
                                    use_rope)

    # ---- decode: one token at a time against the cache ---------------
    def step(carry, n):
        tok, ck, cv = carry
        pos = Tp + n
        x1 = embed(tok[:, None], pos)  # [b, 1, d]
        h1, (ck, cv) = jax.lax.scan(
            lambda h1, inp: decode_layer(h1, inp, pos),
            x1, (params, ck, cv))
        nxt = pick(logits_of(h1[:, 0]), n + 1)
        return (nxt, ck, cv), nxt

    if N == 0:
        return out(Out=prompt)
    # prefill already produced token Tp; the scan decodes the remaining
    # N - 1 (emitting each step's OWN result — no wasted final step)
    (_, _, _), toks = jax.lax.scan(
        step, (next_tok, cache_k, cache_v), jnp.arange(N - 1))
    generated = jnp.concatenate(
        [next_tok[:, None], jnp.moveaxis(toks, 0, 1)], axis=1)  # [b, N]
    return out(Out=jnp.concatenate(
        [prompt, generated.astype(prompt.dtype)], axis=1))


@register_op("transformer_stack_beam_search", optional_inputs=("PosEmb",))
def transformer_stack_beam_search(attrs, ins):
    """Beam search over the KV-cache decode path.

    Same inputs as transformer_stack_generate; attrs: num_heads,
    max_new_tokens, beam_size, length_penalty (GNMT-style
    ((5+len)/6)^alpha score normalisation), eos_id (-1 = none).
    Out [b, K, Tp + N] int (beams sorted best-first) and
    Scores [b, K] f32 (length-normalised log-probs).

    The beam dimension rides the batch axis (caches live at [L, b*K, ...])
    and every step reorders each layer's cache by the surviving beams'
    parent index — one gather per layer, the TPU-native equivalent of the
    reference's beam_search op family shuffling LoD rows
    (/root/reference/paddle/operators/beam_search_op.cc).
    """
    (prompt, tok_emb, pos_emb, ln_s, ln_b, head_w,
     params) = _unpack_lm_ins(ins)
    num_heads = attrs["num_heads"]
    num_kv_heads = attrs.get("num_kv_heads") or num_heads
    use_rope = attrs.get("use_rope", False)
    N = attrs["max_new_tokens"]
    K = attrs.get("beam_size", 4)
    alpha = attrs.get("length_penalty") or 0.0
    eos_id = attrs.get("eos_id", -1)
    if eos_id is None:
        eos_id = -1
    b, Tp = prompt.shape
    L, d = params["ln1_s"].shape
    V = head_w.shape[1]
    Ttot = Tp + N
    if pos_emb is not None and Ttot > pos_emb.shape[0]:
        raise ValueError(
            f"prompt {Tp} + {N} new tokens exceeds max_len "
            f"{pos_emb.shape[0]}")
    if N < 1:
        raise ValueError("beam search needs max_new_tokens >= 1")
    if not 0 < K <= V:
        raise ValueError(f"beam_size {K} outside [1, vocab {V}]")
    embed = _embed_fn(tok_emb, pos_emb)
    logits_of = _logits_fn(ln_s, ln_b, head_w)

    # ---- prefill over the bare batch, then tile to beams --------------
    h, (ks, vs) = _prefill(params, embed(prompt, 0), num_heads, b, Tp,
                           num_kv_heads, use_rope)
    pad = [(0, 0)] * 5
    pad[3] = (0, N)
    cache_k = jnp.repeat(jnp.pad(ks, pad), K, axis=1)  # [L, b*K, Hkv, T, dh]
    cache_v = jnp.repeat(jnp.pad(vs, pad), K, axis=1)

    # first expansion: top-K tokens of the prompt's next-token distribution
    logp0 = jax.nn.log_softmax(logits_of(h[:, -1]), axis=-1)  # [b, V]
    scores, tok0 = jax.lax.top_k(logp0, K)  # [b, K] each
    tokens = jnp.full((b, K, N), eos_id if eos_id >= 0 else 0,
                      dtype=prompt.dtype)
    tokens = tokens.at[:, :, 0].set(tok0.astype(prompt.dtype))
    alive = (tok0 != eos_id) if eos_id >= 0 else jnp.ones((b, K), bool)
    decode_layer = _decode_layer_fn(params, num_heads, d, num_kv_heads,
                                    use_rope)

    def step(carry, n):
        tokens, scores, alive, ck, cv = carry
        pos = Tp + 1 + n
        cur = jax.lax.dynamic_index_in_dim(tokens, n, 2,
                                           keepdims=False)  # [b, K]
        x1 = embed(cur.reshape(b * K)[:, None], pos - 1)  # query at pos-1
        h1, (ck, cv) = jax.lax.scan(
            lambda h1, inp: decode_layer(h1, inp, pos - 1),
            x1, (params, ck, cv))
        logp = jax.nn.log_softmax(logits_of(h1[:, 0]),
                                  axis=-1).reshape(b, K, V)
        # finished beams: only the eos continuation keeps their score
        if eos_id >= 0:
            frozen = jnp.full((V,), -jnp.inf).at[eos_id].set(0.0)
            logp = jnp.where(alive[:, :, None], logp, frozen[None, None])
        cand = scores[:, :, None] + logp  # [b, K, V]
        scores_new, flat_idx = jax.lax.top_k(cand.reshape(b, K * V), K)
        parent = flat_idx // V  # [b, K]
        tok = (flat_idx % V).astype(tokens.dtype)

        # reorder beam state by parent
        batch_ix = jnp.arange(b)[:, None]
        tokens = tokens[batch_ix, parent]  # [b, K, N]
        alive_p = alive[batch_ix, parent]
        tokens = jax.lax.dynamic_update_index_in_dim(
            tokens, tok, n + 1, 2)
        alive = alive_p & (tok != eos_id) if eos_id >= 0 \
            else jnp.ones((b, K), bool)
        # caches: [L, b*K, ...] gather along the beam-batch axis
        flat_parent = (jnp.arange(b)[:, None] * K + parent).reshape(b * K)
        ck = ck[:, flat_parent]
        cv = cv[:, flat_parent]
        return (tokens, scores_new, alive, ck, cv), None

    # zero-length scan (N == 1) returns the carry unchanged
    (tokens, scores, alive, _, _), _ = jax.lax.scan(
        step, (tokens, scores, alive, cache_k, cache_v),
        jnp.arange(N - 1))

    if alpha:
        # GNMT length normalisation over generated (non-frozen) length
        if eos_id >= 0:
            gen_len = jnp.minimum(
                jnp.argmax(tokens == eos_id, axis=2) + 1, N).astype(
                jnp.float32)
            gen_len = jnp.where((tokens == eos_id).any(axis=2), gen_len,
                                float(N))
        else:
            gen_len = jnp.full((b, K), float(N))
        norm = ((5.0 + gen_len) / 6.0) ** alpha
        scores = scores / norm
    order = jnp.argsort(-scores, axis=1)
    batch_ix = jnp.arange(b)[:, None]
    tokens = tokens[batch_ix, order]
    scores = scores[batch_ix, order]
    prompts = jnp.repeat(prompt[:, None, :], K, axis=1)
    return out(Out=jnp.concatenate([prompts, tokens], axis=2),
               Scores=scores)


def _window_verify_fn(params, num_heads, d, num_kv_heads=None,
                      use_rope=False):
    """Forward a w-token window through ALL layers against the cache
    (block-causal: window token i attends cache rows <= pos0 + i), writing
    the window's K/V at rows pos0..pos0+w-1. Returns fn(xw, ck, cv, pos0)
    -> (hidden [b, w, d], ck, cv) — the verify pass of speculative
    decoding, and exactly a prefill when the cache is empty."""
    from ..kernels.flash_attention import reference_attention

    def run(xw, ck, cv, pos0):
        def layer(hw, inp):
            layer_p, ck_l, cv_l = inp
            q, k, v = _attn_proj(layer_p, hw, num_heads, num_kv_heads,
                                 use_rope, pos0=pos0)
            ck_l = jax.lax.dynamic_update_slice_in_dim(ck_l, k, pos0, 2)
            cv_l = jax.lax.dynamic_update_slice_in_dim(cv_l, v, pos0, 2)
            ctx = reference_attention(q, ck_l, cv_l, causal=True,
                                      q_pos0=pos0)
            ctx = ctx.transpose(0, 2, 1, 3).reshape(
                hw.shape[0], hw.shape[1], d)
            return _attn_out_ffn(layer_p, hw, ctx), (ck_l, cv_l)

        return jax.lax.scan(layer, xw, (params, ck, cv))

    return run


@register_op("transformer_stack_speculative_generate",
             optional_inputs=("PosEmb",))
def transformer_stack_speculative_generate(attrs, ins):
    """Self-speculative greedy decoding: an early-exit draft proposes,
    the full stack verifies.

    Same inputs as transformer_stack_generate plus a draft head
    (DraftLnS/DraftLnB [d], DraftHeadW [d, V]); attrs: num_heads,
    max_new_tokens, draft_layers (k < L), gamma (proposals per round).

    Each round the DRAFT — the first k layers of the SAME stack plus its
    own head — decodes gamma tokens through the shared cache's first k
    layer planes; the full L-layer stack then scores the whole window in
    ONE block-causal pass, the longest agreeing prefix is accepted (plus
    the target's correction/bonus token), and the loop advances. Because
    acceptance only keeps tokens the full stack itself argmaxes, the
    output is EXACTLY the plain greedy decode — the draft controls speed,
    never content (verified by test). Batch rows advance in lockstep at
    the batch-min acceptance, keeping every cache update uniform.

    Out [b, Tp + N] int; Rounds [1] int32 (verify rounds taken — the
    speedup diagnostic: plain decode would take N).
    """
    (prompt, tok_emb, pos_emb, ln_s, ln_b, head_w,
     params) = _unpack_lm_ins(ins)
    d_ln_s = single(ins, "DraftLnS")
    d_ln_b = single(ins, "DraftLnB")
    d_head_w = single(ins, "DraftHeadW")
    num_heads = attrs["num_heads"]
    num_kv_heads = attrs.get("num_kv_heads") or num_heads
    use_rope = attrs.get("use_rope", False)
    N = attrs["max_new_tokens"]
    k_layers = attrs["draft_layers"]
    gamma = attrs.get("gamma", 4)
    b, Tp = prompt.shape
    L, d = params["ln1_s"].shape
    if not 0 < k_layers < L:
        raise ValueError(f"draft_layers {k_layers} outside [1, {L - 1}]")
    if N < 1 or gamma < 1:
        raise ValueError("max_new_tokens and gamma must be >= 1")
    # cache slack: a round may write gamma + 1 rows past the last emit
    Ttot = Tp + N + gamma + 1
    if pos_emb is not None and Ttot > pos_emb.shape[0]:
        raise ValueError(
            f"prompt {Tp} + {N} new tokens (+{gamma + 1} speculative "
            f"slack) exceeds max_len {pos_emb.shape[0]}")
    embed = _embed_fn(tok_emb, pos_emb)
    logits_of = _logits_fn(ln_s, ln_b, head_w)
    draft_logits_of = _logits_fn(d_ln_s, d_ln_b, d_head_w)
    draft_params = {key: p[:k_layers] for key, p in params.items()}
    draft_layer = _decode_layer_fn(draft_params, num_heads, d,
                                   num_kv_heads, use_rope)
    verify = _window_verify_fn(params, num_heads, d, num_kv_heads,
                               use_rope)

    # ---- prefill: the full stack over the prompt -----------------------
    h, (ks, vs) = _prefill(params, embed(prompt, 0), num_heads, b, Tp,
                           num_kv_heads, use_rope)
    pad = [(0, 0)] * 5
    pad[3] = (0, Ttot - Tp)
    cache_k = jnp.pad(ks, pad)
    cache_v = jnp.pad(vs, pad)
    cur = jnp.argmax(logits_of(h[:, -1]), axis=-1)  # token at pos Tp

    tokens = jnp.zeros((b, N + gamma + 1), prompt.dtype)
    tokens = tokens.at[:, 0].set(cur.astype(prompt.dtype))

    def round_body(carry):
        tokens, n, cur, pos, rounds, ck, cv = carry
        # pos = cache rows filled (cur sits at position pos, unprocessed)

        # 1. draft chain: k-layer incremental decode of gamma proposals.
        # Only the first k_layers cache planes thread through the scan —
        # carrying the full L-layer cache would rewrite it per proposal.
        def draft_step(dcarry, i):
            dtok, dck, dcv = dcarry
            x1 = embed(dtok[:, None], pos + i)
            h1, (dck, dcv) = jax.lax.scan(
                lambda h1, inp: draft_layer(h1, inp, pos + i),
                x1, (draft_params, dck, dcv))
            nxt = jnp.argmax(draft_logits_of(h1[:, 0]), axis=-1)
            return (nxt.astype(dtok.dtype), dck, dcv), nxt

        (_, dck, dcv), dtoks = jax.lax.scan(
            draft_step, (cur, ck[:k_layers], cv[:k_layers]),
            jnp.arange(gamma))
        ck = jax.lax.dynamic_update_slice_in_dim(ck, dck, 0, 0)
        cv = jax.lax.dynamic_update_slice_in_dim(cv, dcv, 0, 0)
        dtoks = jnp.moveaxis(dtoks, 0, 1)  # [b, gamma]

        # 2. verify: full stack over [cur, d_0..d_{gamma-1}] in one pass
        window = jnp.concatenate(
            [cur[:, None], dtoks.astype(cur.dtype)], axis=1)
        xw = embed(window, pos)
        hw, (ck, cv) = verify(xw, ck, cv, pos)
        t = jnp.argmax(logits_of(
            hw.reshape(b * (gamma + 1), d)), axis=-1).reshape(
            b, gamma + 1)  # target tokens for positions pos+1..pos+g+1

        # 3. lockstep acceptance: batch-min longest agreeing prefix
        agree = (t[:, :gamma] == dtoks)  # [b, gamma]
        acc_rows = jnp.sum(jnp.cumprod(agree.astype(jnp.int32), axis=1),
                           axis=1)  # per-row accepted count
        a = jnp.min(acc_rows)  # lockstep
        # emit t_0..t_a (a+1 tokens: accepted + correction/bonus)
        for i in range(gamma + 1):
            tokens = jnp.where(
                i <= a,
                jax.lax.dynamic_update_index_in_dim(
                    tokens, t[:, i].astype(tokens.dtype), n + 1 + i, 1),
                tokens)
        cur = jax.lax.dynamic_index_in_dim(t, a, 1, keepdims=False)
        return (tokens, n + 1 + a, cur.astype(tokens.dtype),
                pos + 1 + a, rounds + 1, ck, cv)

    def cond(carry):
        # tokens[0] is pre-emitted by the prefill; indices 0..n are
        # filled, so N emissions means n >= N - 1
        return carry[1] < N - 1

    init_n = jnp.asarray(0, jnp.int32)
    tokens, n, cur, pos, rounds, cache_k, cache_v = jax.lax.while_loop(
        cond, round_body,
        (tokens, init_n, cur.astype(tokens.dtype),
         jnp.asarray(Tp, jnp.int32), jnp.asarray(0, jnp.int32),
         cache_k, cache_v))
    out_ids = jnp.concatenate(
        [prompt, tokens[:, :N].astype(prompt.dtype)], axis=1)
    return out(Out=out_ids, Rounds=rounds.reshape(1))


# ---------------------------------------------------------------------------
# Slot-cache decode ops: the continuous-batching serving path
# (paddle_tpu/serving/generation.py). The KV cache is a SLOT TABLE
# [L, S, Hkv, Tmax, dh] living in the scope as persistable state: requests
# claim a slot, prefill scatters their prompt K/V into it, and every decode
# step advances ALL slots one token (each at its own position) — finished
# sequences vacate their slot and new requests join mid-flight. Both ops
# read AND write the cache variables, so the executor threads them as
# donated read-write state (in-place buffer update, no cache copy per step).
# ---------------------------------------------------------------------------

@register_op("transformer_stack_slot_prefill", optional_inputs=("PosEmb",),
             needs_rng=lambda attrs: (attrs.get("temperature") or 0) > 0)
def transformer_stack_slot_prefill(attrs, ins, rng=None):
    """Prefill a batch of prompts into their cache slots.

    Prompt [b, Tp] int (right-padded to the bucket width), SlotIds [b]
    int32 (target slot per row; duplicate ids are only legal for a scrap
    slot), Lengths [b] int32 (true prompt lengths, 1..Tp), CacheK/CacheV
    [L, S, Hkv, Tmax, dh], plus the shared LM weights
    (transformer_stack_generate's contract). Returns NextTok [b] — the
    first generated token per row, from the hidden state at each row's
    true last prompt position — and the caches with rows 0..Tp-1 of each
    target slot overwritten. Pad rows beyond a row's length write pad K/V
    into rows length..Tp-1, which decode never attends (its per-slot
    length mask stops at the current position) and progressively
    overwrites.
    """
    prompt = single(ins, "Prompt")
    slot_ids = single(ins, "SlotIds").astype(jnp.int32)
    lengths = single(ins, "Lengths").astype(jnp.int32)
    cache_k = single(ins, "CacheK")
    cache_v = single(ins, "CacheV")
    tok_emb = single(ins, "TokEmb")
    pos_emb = maybe(ins, "PosEmb")
    ln_s, ln_b = single(ins, "FinalLnS"), single(ins, "FinalLnB")
    head_w = single(ins, "HeadW")
    params = {key: single(ins, slot) for slot, key in _STACK_SLOTS.items()}
    num_heads = attrs["num_heads"]
    num_kv_heads = attrs.get("num_kv_heads") or num_heads
    use_rope = attrs.get("use_rope", False)
    b, Tp = prompt.shape
    Tmax = cache_k.shape[3]
    if Tp > Tmax:
        raise ValueError(f"prompt bucket {Tp} exceeds cache length {Tmax}")
    if pos_emb is not None and Tp > pos_emb.shape[0]:
        raise ValueError(f"prompt bucket {Tp} exceeds max_len "
                         f"{pos_emb.shape[0]}")
    embed = _embed_fn(tok_emb, pos_emb)
    pick = _make_pick(attrs.get("temperature") or 0.0,
                      attrs.get("top_k") or 0, head_w.shape[1], rng)
    h, (ks, vs) = _prefill(params, embed(prompt, 0), num_heads, b, Tp,
                           num_kv_heads, use_rope)
    last = h[jnp.arange(b), jnp.clip(lengths, 1, Tp) - 1]  # [b, d]
    next_tok = pick(_logits_fn(ln_s, ln_b, head_w)(last), 0)
    # ks/vs [L, b, Hkv, Tp, dh] -> scatter each row into its slot's rows
    # 0..Tp-1 (one advanced index: the batch axis maps onto slot ids)
    cache_k = cache_k.at[:, slot_ids, :, :Tp, :].set(ks)
    cache_v = cache_v.at[:, slot_ids, :, :Tp, :].set(vs)
    return out(NextTok=next_tok.astype(prompt.dtype),
               CacheK=cache_k, CacheV=cache_v)


@register_op("transformer_stack_slot_decode", optional_inputs=("PosEmb",),
             needs_rng=lambda attrs: (attrs.get("temperature") or 0) > 0)
def transformer_stack_slot_decode(attrs, ins, rng=None):
    """One decode step over EVERY cache slot, each at its own position.

    Tok [S] int (the pending token per slot — its K/V is not yet in the
    cache), Pos [S] int32 (that token's sequence position == cache rows
    already filled for the slot), CacheK/CacheV [L, S, Hkv, Tmax, dh],
    plus the shared LM weights. Returns NextTok [S] and the caches with
    row Pos[s] of every slot s overwritten by Tok's K/V.

    The slot axis IS the batch axis, so the compiled shape never depends
    on which slots are occupied — the one-compile steady state of
    continuous batching (vacant slots compute a garbage token the host
    ignores; their row-Pos write lands in a region the next prefill
    overwrites). Attention masks each slot to rows <= Pos[s] via the
    per-row lengths plane, so stale rows beyond a slot's position are
    never visible.
    """
    tok = single(ins, "Tok")
    pos = single(ins, "Pos").astype(jnp.int32)
    cache_k = single(ins, "CacheK")
    cache_v = single(ins, "CacheV")
    tok_emb = single(ins, "TokEmb")
    pos_emb = maybe(ins, "PosEmb")
    ln_s, ln_b = single(ins, "FinalLnS"), single(ins, "FinalLnB")
    head_w = single(ins, "HeadW")
    params = {key: single(ins, slot) for slot, key in _STACK_SLOTS.items()}
    num_heads = attrs["num_heads"]
    num_kv_heads = attrs.get("num_kv_heads") or num_heads
    use_rope = attrs.get("use_rope", False)
    S = tok.shape[0]
    if S != cache_k.shape[1]:
        raise ValueError(f"Tok has {S} slots but the cache holds "
                         f"{cache_k.shape[1]}")
    L, d = params["ln1_s"].shape
    Tmax = cache_k.shape[3]
    pos = jnp.clip(pos, 0, Tmax - 1)
    x = tok_emb[tok]
    if pos_emb is not None:
        x = x + pos_emb[jnp.clip(pos, 0, pos_emb.shape[0] - 1)]
    h1 = x[:, None, :]  # [S, 1, d]
    pick = _make_pick(attrs.get("temperature") or 0.0,
                      attrs.get("top_k") or 0, head_w.shape[1], rng)
    srange = jnp.arange(S)

    def layer(h1, inp):
        layer_p, ck_l, cv_l = inp  # caches [S, Hkv, Tmax, dh]
        q, k, v = _attn_proj(layer_p, h1, num_heads, num_kv_heads,
                             use_rope, pos0=pos)
        Hkv = k.shape[1]
        ix = (srange[:, None], jnp.arange(Hkv)[None, :], pos[:, None])
        ck_l = ck_l.at[ix].set(k[:, :, 0, :])
        cv_l = cv_l.at[ix].set(v[:, :, 0, :])
        from ..kernels.flash_attention import reference_attention

        ctx = reference_attention(q, ck_l, cv_l, lengths=pos + 1)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(S, 1, d)
        return _attn_out_ffn(layer_p, h1, ctx), (ck_l, cv_l)

    h1, (cache_k, cache_v) = jax.lax.scan(layer, h1,
                                          (params, cache_k, cache_v))
    nxt = pick(_logits_fn(ln_s, ln_b, head_w)(h1[:, 0]), 0)
    return out(NextTok=nxt.astype(tok.dtype),
               CacheK=cache_k, CacheV=cache_v)


# ---------------------------------------------------------------------------
# Paged-cache decode ops: the block-table serving path (vLLM's
# PagedAttention layout on the slot-op machinery). The KV cache is a PAGE
# POOL [L, N, Hkv, page_size, dh] living in the scope; a per-row int32
# block table maps logical positions to physical pages, so a sequence
# holds exactly ceil(len / page_size) pages instead of a dense Tmax row —
# and a page shared by several sequences (a common system prompt) is
# stored ONCE, each sharer's table pointing at the same physical page.
# Page 0 is the scrap page: padding rows and vacant decode slots write
# there and nothing ever attends to it. Both ops read AND write the pool,
# so the executor threads it as donated read-write state exactly like the
# dense slot table. (The gather materialises each row's table-width
# context per layer — same decode HBM traffic as the dense path; the win
# is CAPACITY. A Pallas per-page-DMA kernel is the follow-on TPU lever.)
# ---------------------------------------------------------------------------

_SAMPLING_SLOTS = ("Temperature", "TopK", "TopP", "Seed", "Step", "Mask")


def _row_sampling(ins):
    """The per-row sampling plane, when fed: (temperature [rows], top_k
    [rows], top_p [rows], seed [rows], step [rows], mask [rows, V] or
    None) — or None when the program predates per-request sampling (the
    legacy engine-wide attrs path)."""
    temp = maybe(ins, "Temperature")
    if temp is None:
        return None
    return (temp, single(ins, "TopK"), single(ins, "TopP"),
            single(ins, "Seed"), single(ins, "Step"), maybe(ins, "Mask"))


def _pick_rows(attrs, ins, rng, vocab, logits, step0=0):
    """Next-token selection for the paged decode family: the per-row
    plane (kernels/sampling.sample_rows — seeds are INPUTS, the scope
    RNG stays untouched) when fed, else the legacy engine-wide
    attrs/rng path."""
    from ..kernels.sampling import sample_rows

    plane = _row_sampling(ins)
    if plane is None:
        pick = _make_pick(attrs.get("temperature") or 0.0,
                          attrs.get("top_k") or 0, vocab, rng)
        return pick(logits, step0)
    temp, top_k, top_p, seed, step, mask = plane
    return sample_rows(logits, temp, top_k, top_p, seed, step, mask)


def _maybe_topk(attrs, ins, logits, outs):
    """Attach TopV/TopI (each row's top-``emit_topk`` masked log-probs)
    to ``outs`` when the program asks for the beam plane."""
    k = attrs.get("emit_topk") or 0
    if k:
        from ..kernels.sampling import top_logprobs

        vals, ids = top_logprobs(logits, int(k), maybe(ins, "Mask"))
        outs["TopV"], outs["TopI"] = [vals], [ids]
    return outs


def _gather_pages(pool_l, table):
    """pool_l [N, Hkv, ps, dh] gathered by table [b, P] -> the flattened
    context [b, Hkv, P*ps, dh]: flattened position j holds the token at
    sequence position j (table entry i covers positions i*ps..(i+1)*ps-1,
    so position order survives the transpose/reshape)."""
    b, P = table.shape
    _, hkv, ps, dh = pool_l.shape
    ctx = pool_l[table]  # [b, P, Hkv, ps, dh]
    return ctx.transpose(0, 2, 1, 3, 4).reshape(b, hkv, P * ps, dh)


@register_op("transformer_stack_paged_prefill",
             optional_inputs=("PosEmb",) + _SAMPLING_SLOTS,
             needs_rng=lambda attrs: (attrs.get("temperature") or 0) > 0)
def transformer_stack_paged_prefill(attrs, ins, rng=None):
    """Prefill ONE CHUNK of each row's prompt into its block-table pages.

    Chunk [b, Tc] int (right-padded), StartPos [b] int32 (absolute
    sequence position of each row's first chunk token — 0 for a plain
    prefill, the shared-prefix length for a prefix-cache hit, k*chunk for
    the k-th chunk of a streaming long prompt), Lengths [b] int32 (valid
    tokens in THIS chunk, 0..Tc; 0 marks a padding row), BlockTable
    [b, P] int32 (the row's full logical->physical page map; padding
    entries 0), CacheK/CacheV [L, N, Hkv, ps, dh] page pools, plus the
    shared LM weights. attrs carry ``page_size`` next to the decode-op
    set. Returns NextTok [b] — argmax/sample from each row's LAST VALID
    chunk position (the first generated token when this chunk completes
    the prompt; garbage otherwise) — and the pools with the chunk's K/V
    scattered into rows StartPos..StartPos+Lengths-1 of each row's pages.

    Queries attend the row's WHOLE gathered context block-causally (chunk
    token at absolute position p sees cached position j iff j <= p), so a
    later chunk attends every earlier chunk's pages and a shared-prefix
    row attends the shared pages it never prefilled — token-exact vs the
    dense one-shot prefill. Pages beyond a row's extent sit at flattened
    positions > p and are masked by the same rule.

    Optional per-row sampling plane (Temperature/TopK/TopP/Seed/Step [b]
    + Mask [b, V]): when fed, NextTok comes from
    ``kernels.sampling.sample_rows`` — each row's policy and seed ride
    the request, the scope RNG is never consumed, and the token is a
    pure function of (request, seed, step). ``emit_topk`` > 0 adds
    TopV/TopI [b, emit_topk] (masked top-k log-probs of the last valid
    position) — the beam-search expansion plane.
    """
    # per-row sampling slots, read via _row_sampling/_maybe_topk:
    # "Temperature", "TopK", "TopP", "Seed", "Step", "Mask"
    chunk = single(ins, "Chunk")
    start = single(ins, "StartPos").astype(jnp.int32)
    lengths = single(ins, "Lengths").astype(jnp.int32)
    table = single(ins, "BlockTable").astype(jnp.int32)
    cache_k = single(ins, "CacheK")
    cache_v = single(ins, "CacheV")
    tok_emb = single(ins, "TokEmb")
    pos_emb = maybe(ins, "PosEmb")
    ln_s, ln_b = single(ins, "FinalLnS"), single(ins, "FinalLnB")
    head_w = single(ins, "HeadW")
    params = {key: single(ins, slot) for slot, key in _STACK_SLOTS.items()}
    num_heads = attrs["num_heads"]
    num_kv_heads = attrs.get("num_kv_heads") or num_heads
    use_rope = attrs.get("use_rope", False)
    b, Tc = chunk.shape
    ps = cache_k.shape[3]
    P = table.shape[1]
    d = params["ln1_s"].shape[1]
    # absolute positions + per-token page targets (padding -> scrap 0)
    pos = start[:, None] + jnp.arange(Tc, dtype=jnp.int32)[None, :]
    valid = jnp.arange(Tc, dtype=jnp.int32)[None, :] < lengths[:, None]
    entry = jnp.clip(pos // ps, 0, P - 1)
    page_id = jnp.where(
        valid, jnp.take_along_axis(table, entry, axis=1), 0)
    page_row = jnp.where(valid, pos % ps, 0)
    x = tok_emb[chunk]
    if pos_emb is not None:
        x = x + pos_emb[jnp.clip(pos, 0, pos_emb.shape[0] - 1)]
    from ..kernels.flash_attention import reference_attention

    def layer(h, inp):
        layer_p, ck_l, cv_l = inp  # pools [N, Hkv, ps, dh]
        q, k, v = _attn_proj(layer_p, h, num_heads, num_kv_heads,
                             use_rope, pos0=start)
        # k/v [b, Hkv, Tc, dh] -> page (page_id, page_row) per token
        ck_l = ck_l.at[page_id, :, page_row, :].set(k.transpose(0, 2, 1, 3))
        cv_l = cv_l.at[page_id, :, page_row, :].set(v.transpose(0, 2, 1, 3))
        ctx = reference_attention(q, _gather_pages(ck_l, table),
                                  _gather_pages(cv_l, table),
                                  causal=True, q_pos0=start)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(b, Tc, d)
        return _attn_out_ffn(layer_p, h, ctx), (ck_l, cv_l)

    h, (cache_k, cache_v) = jax.lax.scan(layer, x,
                                         (params, cache_k, cache_v))
    last = h[jnp.arange(b), jnp.clip(lengths, 1, Tc) - 1]  # [b, d]
    logits = _logits_fn(ln_s, ln_b, head_w)(last)
    nxt = _pick_rows(attrs, ins, rng, head_w.shape[1], logits)
    outs = out(NextTok=nxt.astype(chunk.dtype),
               CacheK=cache_k, CacheV=cache_v)
    return _maybe_topk(attrs, ins, logits, outs)


@register_op("transformer_stack_paged_decode",
             optional_inputs=("PosEmb",) + _SAMPLING_SLOTS,
             needs_rng=lambda attrs: (attrs.get("temperature") or 0) > 0)
def transformer_stack_paged_decode(attrs, ins, rng=None):
    """One decode step over every slot's paged context.

    Tok [S] int (the pending token per slot), Pos [S] int32 (its sequence
    position == rows already cached for the slot), BlockTable [S, P]
    int32 (per-slot page map; vacant slots feed all-zeros + Pos 0, so
    their write lands in the scrap page), CacheK/CacheV [L, N, Hkv, ps,
    dh] page pools, plus the shared LM weights. Returns NextTok [S] and
    the pools with each slot's token K/V written at page
    BlockTable[s, Pos//ps] row Pos%ps.

    The slot axis is the batch axis and the table width is static, so the
    compiled shape never depends on occupancy or sequence lengths — the
    same one-compile steady state as the dense slot decode, over a pool
    sized by TOKENS IN FLIGHT instead of slots*Tmax.

    Optional per-row sampling plane (Temperature/TopK/TopP/Seed/Step [S]
    + Mask [S, V]): per-REQUEST decode policy inside the one compiled
    step — greedy, temperature, top-k, top-p, and grammar-masked rows
    mix freely, and each row's token depends only on (its context, its
    policy, its seed, its step). ``emit_topk`` > 0 adds TopV/TopI
    [S, emit_topk] — beam hypotheses expand from these without a second
    model pass.
    """
    # per-row sampling slots, read via _row_sampling/_maybe_topk:
    # "Temperature", "TopK", "TopP", "Seed", "Step", "Mask"
    tok = single(ins, "Tok")
    pos = single(ins, "Pos").astype(jnp.int32)
    table = single(ins, "BlockTable").astype(jnp.int32)
    cache_k = single(ins, "CacheK")
    cache_v = single(ins, "CacheV")
    tok_emb = single(ins, "TokEmb")
    pos_emb = maybe(ins, "PosEmb")
    ln_s, ln_b = single(ins, "FinalLnS"), single(ins, "FinalLnB")
    head_w = single(ins, "HeadW")
    params = {key: single(ins, slot) for slot, key in _STACK_SLOTS.items()}
    num_heads = attrs["num_heads"]
    num_kv_heads = attrs.get("num_kv_heads") or num_heads
    use_rope = attrs.get("use_rope", False)
    S = tok.shape[0]
    if S != table.shape[0]:
        raise ValueError(f"Tok has {S} slots but the block table holds "
                         f"{table.shape[0]}")
    ps = cache_k.shape[3]
    P = table.shape[1]
    d = params["ln1_s"].shape[1]
    pos = jnp.clip(pos, 0, P * ps - 1)
    x = tok_emb[tok]
    if pos_emb is not None:
        x = x + pos_emb[jnp.clip(pos, 0, pos_emb.shape[0] - 1)]
    h1 = x[:, None, :]  # [S, 1, d]
    srange = jnp.arange(S)
    page_id = table[srange, pos // ps]  # [S]
    page_row = pos % ps
    from ..kernels.flash_attention import reference_attention

    def layer(h1, inp):
        layer_p, ck_l, cv_l = inp  # pools [N, Hkv, ps, dh]
        q, k, v = _attn_proj(layer_p, h1, num_heads, num_kv_heads,
                             use_rope, pos0=pos)
        ck_l = ck_l.at[page_id, :, page_row, :].set(k[:, :, 0, :])
        cv_l = cv_l.at[page_id, :, page_row, :].set(v[:, :, 0, :])
        ctx = reference_attention(q, _gather_pages(ck_l, table),
                                  _gather_pages(cv_l, table),
                                  lengths=pos + 1)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(S, 1, d)
        return _attn_out_ffn(layer_p, h1, ctx), (ck_l, cv_l)

    h1, (cache_k, cache_v) = jax.lax.scan(layer, h1,
                                          (params, cache_k, cache_v))
    logits = _logits_fn(ln_s, ln_b, head_w)(h1[:, 0])
    nxt = _pick_rows(attrs, ins, rng, head_w.shape[1], logits)
    outs = out(NextTok=nxt.astype(tok.dtype),
               CacheK=cache_k, CacheV=cache_v)
    return _maybe_topk(attrs, ins, logits, outs)


@register_op("kv_cache_page_copy")
def kv_cache_page_copy(attrs, ins):
    """Copy whole KV pages inside the pools: the copy-on-write step.

    Src [n] int32, Dst [n] int32 (distinct destination pages),
    CacheK/CacheV [L, N, Hkv, ps, dh]. Writes pool[:, Dst[i]] =
    pool[:, Src[i]] for both pools and echoes Dst as Ok [n] (a fetchable
    witness — the real outputs are the donated pool updates). The serving
    engine runs this when a sequence is about to write into a page whose
    refcount > 1 (a shared prefix page it is diverging from)."""
    src = single(ins, "Src").astype(jnp.int32)
    dst = single(ins, "Dst").astype(jnp.int32)
    cache_k = single(ins, "CacheK")
    cache_v = single(ins, "CacheV")
    cache_k = cache_k.at[:, dst].set(cache_k[:, src])
    cache_v = cache_v.at[:, dst].set(cache_v[:, src])
    return out(Ok=dst, CacheK=cache_k, CacheV=cache_v)

"""Pipelined transformer stack op: L pre-LN blocks with stacked weights.

The layer stack carries every weight with a leading layer axis [L, ...],
which buys two TPU-native wins at once: a single ``lax.scan`` over layers
(one compiled block body instead of L inlined copies — the XLA compile-time
idiom for deep stacks), and pipeline parallelism for free — when the
executor mesh has a ``pp`` axis the same stacked tensors shard their layer
axis across stages and run under the GPipe schedule
(parallel/pipeline.gpipe). The reference's closest machinery places whole
layer ranges on devices by config and moves activations by memcpy
(/root/reference/paddle/gserver/gradientmachines/ParallelNeuralNetwork.cpp);
here placement is a sharding spec and movement is an ICI ppermute.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op
from ..kernels.flash_attention import flash_attention
from .common import amp_cast, mxu_precision, out, single

_EPS = 1e-5


def _ln(x, scale, bias):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + _EPS) * scale + bias


def _block(p, x, num_heads, causal):
    """One pre-LN transformer block; p holds per-layer (no leading dim)
    weights: ln1_s, ln1_b, qkv_w, out_w, ln2_s, ln2_b, ff_w1, ff_b1,
    ff_w2, ff_b2."""
    b, T, d = x.shape
    head_d = d // num_heads

    h = _ln(x, p["ln1_s"], p["ln1_b"])
    h_c, qkv_c = amp_cast(h, p["qkv_w"])
    qkv = jnp.einsum("btd,de->bte", h_c, qkv_c,
                     precision=mxu_precision()).astype(x.dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)

    def heads(t):
        return t.reshape(b, T, num_heads, head_d).transpose(0, 2, 1, 3)

    ctx = flash_attention(heads(q), heads(k), heads(v), causal=causal)
    ctx = ctx.transpose(0, 2, 1, 3).reshape(b, T, d)
    ctx_c, ow_c = amp_cast(ctx, p["out_w"])
    attn = jnp.einsum("btd,de->bte", ctx_c, ow_c,
                      precision=mxu_precision()).astype(x.dtype)
    x = x + attn

    h2 = _ln(x, p["ln2_s"], p["ln2_b"])
    h2_c, w1_c = amp_cast(h2, p["ff_w1"])
    ff = jax.nn.gelu(
        jnp.einsum("btd,df->btf", h2_c, w1_c,
                   precision=mxu_precision()).astype(x.dtype) + p["ff_b1"])
    ff_c, w2_c = amp_cast(ff, p["ff_w2"])
    ff = jnp.einsum("btf,fd->btd", ff_c, w2_c,
                    precision=mxu_precision()).astype(x.dtype) + p["ff_b2"]
    return x + ff


_STACK_SLOTS = {
    "Ln1S": "ln1_s", "Ln1B": "ln1_b", "QkvW": "qkv_w", "OutW": "out_w",
    "Ln2S": "ln2_s", "Ln2B": "ln2_b", "FfW1": "ff_w1", "FfB1": "ff_b1",
    "FfW2": "ff_w2", "FfB2": "ff_b2",
}


@register_op("pipelined_transformer_stack")
def pipelined_transformer_stack(attrs, ins):
    """X [b, T, d] + stacked block weights (leading dim L) -> Out [b, T, d].

    attrs: num_heads, causal, n_microbatches. With a ``pp`` mesh axis the
    stack runs the GPipe schedule (layer axis sharded into stages, each
    stage scanning its local L/S layers); otherwise one scan over all L.
    """
    from ..parallel.context import current_mesh, mesh_axis

    x = single(ins, "X")
    params = {key: single(ins, slot)
              for slot, key in _STACK_SLOTS.items()}
    num_heads = attrs["num_heads"]
    causal = attrs.get("causal", True)

    def scan_layers(p, h):
        def body(carry, layer_p):
            return _block(layer_p, carry, num_heads, causal), None

        h, _ = jax.lax.scan(body, h, p)
        return h

    pipe_axis = attrs.get("pipe_axis") or "pp"
    pp = mesh_axis(pipe_axis)
    L = params["qkv_w"].shape[0]
    if pp > 1:
        from ..parallel.pipeline import gpipe

        if L % pp:
            raise ValueError(
                f"{L} layers not divisible by pipeline size {pp}")
        mesh = current_mesh()
        data_axis = attrs.get("data_axis") or "dp"
        if data_axis not in mesh.axis_names:
            data_axis = None
        y = gpipe(scan_layers, params, x, mesh, axis=pipe_axis,
                  n_microbatches=attrs.get("n_microbatches") or pp,
                  data_axis=data_axis)
        return out(Out=y)
    return out(Out=scan_layers(params, x))

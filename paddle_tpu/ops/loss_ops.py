"""Loss ops.

Covers the reference loss families
(/root/reference/paddle/operators/cross_entropy_op.cc,
softmax_with_cross_entropy_op.cc, hinge_loss_op.cc, huber_loss_op.cc,
log_loss_op.cc, margin_rank_loss_op.cc, rank_loss_op.cc,
squared_l2_distance_op.cc, smooth_l1_loss_op.cc and the legacy CostLayer
zoo in gserver/layers/CostLayer.cpp).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op
from .common import broadcast_to_x, maybe, out, single


def _take_label_prob(x, label):
    """Pick per-row probability at integer label along the last (class)
    axis; works for [N, D] logits with [N]/[N,1] labels and rank-3
    [b, T, D] sequence logits with [b, T]/[b, T, 1] labels alike."""
    lab = label.reshape(x.shape[:-1])[..., None].astype(jnp.int32)
    return jnp.take_along_axis(x, lab, axis=-1)


@register_op("cross_entropy")
def cross_entropy(attrs, ins):
    x = single(ins, "X")  # probabilities [N, D]
    label = single(ins, "Label")
    eps = 1e-12
    if attrs.get("soft_label", False):
        y = -jnp.sum(label * jnp.log(x + eps), axis=-1, keepdims=True)
    else:
        y = -jnp.log(_take_label_prob(x, label) + eps)
    return out(Y=y)


def _softmax_with_ce_grad(attrs, ins, outs, ogs):
    """Fused, numerically-exact gradient: d_logits = (softmax - onehot) * dY,
    emitted in the LOGITS dtype — at LM-head scale ([tokens, vocab]) an f32
    gradient tensor would double the dominant HBM stream of the whole loss
    (the one_hot itself is an iota-compare XLA folds into the subtract)."""
    logits = single(ins, "Logits")
    label = single(ins, "Label")
    sm = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    if attrs.get("soft_label", False):
        grad = sm - label.astype(jnp.float32)
    else:
        onehot = jax.nn.one_hot(label.reshape(logits.shape[:-1]),
                                logits.shape[-1], dtype=sm.dtype)
        grad = sm - onehot
    dy = ogs["Loss"][0].astype(jnp.float32)
    return {"Logits": [(grad * dy).astype(logits.dtype)], "Label": [None]}


@register_op("softmax_with_cross_entropy", grad_fn=_softmax_with_ce_grad)
def softmax_with_cross_entropy(attrs, ins):
    logits = single(ins, "Logits")
    label = single(ins, "Label")
    # Loss reductions always run in f32 (stable under bf16 AMP activations).
    # Hard labels go through the logsumexp form — loss rows need only the
    # two reductions and one gathered logit, so no [N, vocab] log-softmax
    # tensor has to materialise between kernels at LM-head scale. The
    # Softmax output is derived lazily and DCE'd when nothing consumes it.
    x = logits.astype(jnp.float32)
    mx = jax.lax.stop_gradient(jnp.max(x, axis=-1, keepdims=True))
    lse = mx + jnp.log(jnp.sum(jnp.exp(x - mx), axis=-1, keepdims=True))
    if attrs.get("soft_label", False):
        logp = x - lse
        loss = -jnp.sum(label * logp, axis=-1, keepdims=True)
    else:
        loss = lse - _take_label_prob(x, label)
    return {"Softmax": [jnp.exp(x - lse)], "Loss": [loss]}


@register_op("square_error_cost")
def square_error_cost(attrs, ins):
    x = single(ins, "X")
    y = single(ins, "Y")
    return out(Out=jnp.square(x - y))


@register_op("squared_l2_distance")
def squared_l2_distance(attrs, ins):
    x = single(ins, "X")
    y = single(ins, "Y")
    diff = x - y
    return {"sub_result": [diff],
            "Out": [jnp.sum(jnp.square(diff), axis=-1, keepdims=True)]}


@register_op("squared_l2_norm")
def squared_l2_norm(attrs, ins):
    x = single(ins, "X")
    return out(Out=jnp.sum(jnp.square(x)).reshape(1))


@register_op("hinge_loss")
def hinge_loss(attrs, ins):
    logits = single(ins, "Logits")
    labels = single(ins, "Labels").astype(logits.dtype)
    signs = 2.0 * labels - 1.0
    return out(Loss=jnp.maximum(0.0, 1.0 - signs * logits))


@register_op("huber_loss")
def huber_loss(attrs, ins):
    x = single(ins, "X")
    y = single(ins, "Y")
    delta = attrs.get("delta", 1.0)
    r = y - x
    ar = jnp.abs(r)
    loss = jnp.where(ar <= delta, 0.5 * r * r, delta * (ar - 0.5 * delta))
    return {"Residual": [r], "Out": [loss]}


@register_op("log_loss")
def log_loss(attrs, ins):
    p = single(ins, "Predicted")
    y = single(ins, "Labels")
    eps = attrs.get("epsilon", 1e-4)
    return out(Loss=-y * jnp.log(p + eps) - (1.0 - y) * jnp.log(1.0 - p + eps))


@register_op("rank_loss")
def rank_loss(attrs, ins):
    label = single(ins, "Label")
    left = single(ins, "Left")
    right = single(ins, "Right")
    d = left - right
    return out(Out=jnp.log1p(jnp.exp(d)) - label * d)


@register_op("margin_rank_loss")
def margin_rank_loss(attrs, ins):
    label = single(ins, "Label")
    x1 = single(ins, "X1")
    x2 = single(ins, "X2")
    margin = attrs.get("margin", 0.0)
    o = jnp.maximum(0.0, -label * (x1 - x2) + margin)
    return {"Out": [o], "Activated": [(o > 0).astype(x1.dtype)]}


@register_op("smooth_l1_loss")
def smooth_l1_loss(attrs, ins):
    x = single(ins, "X")
    y = single(ins, "Y")
    sigma = attrs.get("sigma", 1.0)
    s2 = sigma * sigma
    diff = x - y
    in_w = maybe(ins, "InsideWeight")
    out_w = maybe(ins, "OutsideWeight")
    if in_w is not None:
        diff = diff * in_w
    ad = jnp.abs(diff)
    elem = jnp.where(ad < 1.0 / s2, 0.5 * s2 * diff * diff, ad - 0.5 / s2)
    if out_w is not None:
        elem = elem * out_w
    return {"Diff": [diff], "Out": [jnp.sum(elem, axis=-1, keepdims=True)]}


@register_op("sigmoid_cross_entropy_with_logits")
def sigmoid_cross_entropy_with_logits(attrs, ins):
    x = single(ins, "X")
    label = single(ins, "Label")
    loss = jnp.maximum(x, 0.0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    return out(Out=loss)


@register_op("bce_loss")
def bce_loss(attrs, ins):
    x = single(ins, "X")
    label = single(ins, "Label")
    eps = 1e-12
    return out(Out=-(label * jnp.log(x + eps) + (1 - label) * jnp.log(1 - x + eps)))

"""Loss ops.

Covers the reference loss families
(/root/reference/paddle/operators/cross_entropy_op.cc,
softmax_with_cross_entropy_op.cc, hinge_loss_op.cc, huber_loss_op.cc,
log_loss_op.cc, margin_rank_loss_op.cc, rank_loss_op.cc,
squared_l2_distance_op.cc, smooth_l1_loss_op.cc and the legacy CostLayer
zoo in gserver/layers/CostLayer.cpp).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import register_op
from .common import (amp_cast, broadcast_to_x, maybe, mxu_precision,
                     out, single)


def _take_label_prob(x, label):
    """Pick per-row probability at integer label along the last (class)
    axis; works for [N, D] logits with [N]/[N,1] labels and rank-3
    [b, T, D] sequence logits with [b, T]/[b, T, 1] labels alike."""
    lab = label.reshape(x.shape[:-1])[..., None].astype(jnp.int32)
    return jnp.take_along_axis(x, lab, axis=-1)


@register_op("cross_entropy")
def cross_entropy(attrs, ins):
    x = single(ins, "X")  # probabilities [N, D]
    label = single(ins, "Label")
    eps = 1e-12
    if attrs.get("soft_label", False):
        y = -jnp.sum(label * jnp.log(x + eps), axis=-1, keepdims=True)
    else:
        y = -jnp.log(_take_label_prob(x, label) + eps)
    return out(Y=y)


def _softmax_with_ce_grad(attrs, ins, outs, ogs):
    """Fused, numerically-exact gradient: d_logits = (softmax - onehot) * dY,
    emitted in the LOGITS dtype — at LM-head scale ([tokens, vocab]) an f32
    gradient tensor would double the dominant HBM stream of the whole loss
    (the one_hot itself is an iota-compare XLA folds into the subtract)."""
    logits = single(ins, "Logits")
    label = single(ins, "Label")
    sm = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    if attrs.get("soft_label", False):
        grad = sm - label.astype(jnp.float32)
    else:
        eps = attrs.get("label_smoothing", 0.0)
        onehot = jax.nn.one_hot(label.reshape(logits.shape[:-1]),
                                logits.shape[-1], dtype=sm.dtype)
        if eps:
            # smoothed target: (1-eps)*onehot + eps/V uniform mass
            onehot = (1.0 - eps) * onehot + eps / logits.shape[-1]
        grad = sm - onehot
    dy = ogs["Loss"][0].astype(jnp.float32)
    return {"Logits": [(grad * dy).astype(logits.dtype)], "Label": [None]}


@register_op("softmax_with_cross_entropy", grad_fn=_softmax_with_ce_grad)
def softmax_with_cross_entropy(attrs, ins):
    logits = single(ins, "Logits")
    label = single(ins, "Label")
    # Loss reductions always run in f32 (stable under bf16 AMP activations).
    # Hard labels go through the logsumexp form — loss rows need only the
    # two reductions and one gathered logit, so no [N, vocab] log-softmax
    # tensor has to materialise between kernels at LM-head scale. The
    # Softmax output is derived lazily and DCE'd when nothing consumes it.
    x = logits.astype(jnp.float32)
    mx = jax.lax.stop_gradient(jnp.max(x, axis=-1, keepdims=True))
    lse = mx + jnp.log(jnp.sum(jnp.exp(x - mx), axis=-1, keepdims=True))
    if attrs.get("soft_label", False):
        logp = x - lse
        loss = -jnp.sum(label * logp, axis=-1, keepdims=True)
    else:
        loss = lse - _take_label_prob(x, label)
        eps = attrs.get("label_smoothing", 0.0)
        if eps:
            # -sum(((1-eps)*onehot + eps/V) * logp) = lse
            #   - (1-eps)*x_label - (eps/V)*sum(x)
            loss = (1.0 - eps) * loss + eps * (
                lse - jnp.mean(x, axis=-1, keepdims=True))
    return {"Softmax": [jnp.exp(x - lse)], "Loss": [loss]}


def _fhce_chunks(vocab, chunk):
    """(chunk, n_chunks) with n_chunks = ceil(vocab/chunk): the last
    chunk is padded, never shrunk — an awkward vocab (prime, GPT-2's
    50257) must not degrade into a longer sequential loop."""
    c = min(chunk, vocab)
    return c, -(-vocab // c)


def _fhce_w3(wc, chunk, n_chunks, vocab):
    """W [d, vocab] -> [d, n_chunks, chunk], zero-padding the tail chunk.
    Padded columns are masked to -inf logits by the callers."""
    d = wc.shape[0]
    pad = n_chunks * chunk - vocab
    if pad:
        wc = jnp.pad(wc, ((0, 0), (0, pad)))
    return wc.reshape(d, n_chunks, chunk)


def _fhce_gather(logits_c, lab, c0, cols):
    """Per-row logit at ``lab`` when it falls inside this chunk, else 0."""
    local = lab - c0
    inside = (local >= 0) & (local < cols)
    safe = jnp.clip(local, 0, cols - 1)
    picked = jnp.take_along_axis(logits_c, safe[:, None], axis=1)[:, 0]
    return jnp.where(inside, picked, 0.0)


def _fhce_vp_mesh(attrs):
    """The executor mesh when this op instance should lower
    vocab-parallel (attr opt-in + a model axis of size > 1); None means
    the serial chunked path — the SAME program runs on one device."""
    if not attrs.get("vocab_parallel", False):
        return None
    from ..parallel.context import current_mesh, mesh_axis

    if mesh_axis(attrs.get("model_axis", "mp")) <= 1:
        return None
    return current_mesh()


def _fhce_chunk_logits(x2, w3, i, chunk, vocab):
    """Chunk ``i``'s logits in f32, padded columns masked to -inf. The
    ONE recompute kernel shared by forward LSE and backward softmax —
    they must stay bit-identical for the saved-LSE reuse to be valid."""
    wck = jax.lax.dynamic_index_in_dim(w3, i, axis=1, keepdims=False)
    logits = jax.lax.dot_general(
        x2, wck, (((1,), (0,)), ((), ())),
        precision=mxu_precision(),
        preferred_element_type=jnp.float32)
    valid = (i * chunk + jnp.arange(chunk)) < vocab
    return jnp.where(valid[None, :], logits, -jnp.inf), wck


def _fhce_lse_chunk(x2, w3, i, chunk, vocab, lab, carry):
    """One online-logsumexp step over chunk ``i``; carry = (m, s, ll, rs)
    with rs the per-row sum of valid logits (the label-smoothing term).
    Out-of-range labels (< 0 or >= vocab) never gather — callers with
    vocab shards map foreign labels to -1."""
    m, s, ll, rs = carry
    logits, _ = _fhce_chunk_logits(x2, w3, i, chunk, vocab)
    m_c = jnp.max(logits, axis=1)
    m_new = jnp.maximum(m, m_c)
    s = s * jnp.exp(m - m_new) + jnp.sum(
        jnp.exp(logits - m_new[:, None]), axis=1)
    ll = ll + _fhce_gather(logits, lab, i * chunk, chunk)
    rs = rs + jnp.sum(jnp.where(jnp.isneginf(logits), 0.0, logits),
                      axis=1)
    return m_new, s, ll, rs


def _fhce_grad_chunk(x2, w3, i, chunk, vocab, lab, lse2, dl2,
                     smoothing=0.0, full_vocab=None):
    """One backward step over chunk ``i``: (dX contribution [n, d],
    dW chunk [d, chunk]) from g = (softmax - target) * dLoss, where the
    target is the one-hot label or its label-smoothed form. The ONE
    definition shared by the serial and vocab-parallel backwards.
    ``full_vocab``: the GLOBAL vocabulary size the eps/V mass spreads
    over (differs from ``vocab`` on a vocab shard)."""
    logits, wck = _fhce_chunk_logits(x2, w3, i, chunk, vocab)
    p = jnp.exp(logits - lse2)
    local = lab - i * chunk
    target = jax.nn.one_hot(
        jnp.where((local >= 0) & (local < chunk), local, -1),
        chunk, dtype=jnp.float32)
    if smoothing:
        valid = ~jnp.isneginf(logits)
        target = ((1.0 - smoothing) * target
                  + (smoothing / (full_vocab or vocab)) * valid)
    g = ((p - target) * dl2).astype(x2.dtype)
    dx_c = jax.lax.dot_general(
        g, wck, (((1,), (1,)), ((), ())),
        precision=mxu_precision(),
        preferred_element_type=jnp.float32)
    dw_c = jax.lax.dot_general(
        x2, g, (((0,), (0,)), ((), ())),
        precision=mxu_precision(),
        preferred_element_type=jnp.float32)
    return dx_c, dw_c


def _fused_head_ce_grad(attrs, ins, outs, ogs):
    """Chunked backward: recompute each logits chunk, form
    (softmax - onehot) * dLoss in-register, and contract it immediately
    into dX and that chunk's dW rows — the [N, vocab] gradient tensor
    never materializes either. LSE is re-used from the forward's saved
    [N] row (or recomputed chunk-wise if the layer didn't wire it)."""
    x = single(ins, "X")
    w = single(ins, "W")
    label = single(ins, "Label")
    dloss = ogs.get("Loss", [None])[0]
    if dloss is None:
        raise NotImplementedError("fused_head_cross_entropy grad needs "
                                  "Loss@GRAD (LSE is not differentiable)")
    if any(g is not None for g in ogs.get("LSE", [])):
        raise NotImplementedError(
            "fused_head_cross_entropy LSE output is an auxiliary "
            "residual, not a differentiable head")
    xc, wc = amp_cast(x, w)
    lead = x.shape[:-1]
    d = x.shape[-1]
    vocab = w.shape[-1]
    n = int(np.prod(lead))
    x2 = xc.reshape(n, d)
    lab = label.reshape(n).astype(jnp.int32)
    dl = dloss.reshape(n).astype(jnp.float32)
    raw_chunk = attrs.get("chunk", 8192)

    mesh = _fhce_vp_mesh(attrs)
    lse = outs.get("LSE", [None])[0]
    if mesh is not None:
        from ..parallel.vocab_parallel_loss import (vp_fused_head_grad,
                                                   vp_fused_head_lse)

        vp_axis = attrs.get("model_axis", "mp")
        data_axis = attrs.get("data_axis", "dp")
        if lse is None:
            lse = vp_fused_head_lse(x2, wc, lab, raw_chunk, mesh,
                                    vp_axis, data_axis)[0]
        dx, dw = vp_fused_head_grad(
            x2, wc, lab, dl, lse.reshape(n).astype(jnp.float32),
            raw_chunk, mesh, vp_axis, data_axis,
            smoothing=attrs.get("label_smoothing", 0.0))
        return {"X": [dx.reshape(x.shape).astype(x.dtype)],
                "W": [dw.astype(w.dtype)],
                "Label": [None]}
    chunk, n_chunks = _fhce_chunks(vocab, raw_chunk)
    if lse is None:
        lse = _fhce_lse(x2, wc, lab, chunk, n_chunks)[0]
    lse = lse.reshape(n, 1).astype(jnp.float32)
    eps = attrs.get("label_smoothing", 0.0)

    w3 = _fhce_w3(wc, chunk, n_chunks, vocab)
    dl2 = dl[:, None]

    def body(i, carry):
        dx_acc, dw_acc = carry
        dx_c, dw_c = _fhce_grad_chunk(x2, w3, i, chunk, vocab, lab, lse,
                                      dl2, smoothing=eps)
        return (dx_acc + dx_c,
                jax.lax.dynamic_update_index_in_dim(dw_acc, dw_c, i,
                                                    axis=1))

    dx0 = jnp.zeros((n, d), jnp.float32)
    dw0 = jnp.zeros((d, n_chunks, chunk), jnp.float32)
    dx, dw = jax.lax.fori_loop(0, n_chunks, body, (dx0, dw0))
    dw = dw.reshape(d, n_chunks * chunk)[:, :vocab]
    return {"X": [dx.reshape(x.shape).astype(x.dtype)],
            "W": [dw.astype(w.dtype)],
            "Label": [None]}


def _fhce_lse(x2, wc, lab, chunk, n_chunks):
    """(lse, label logit, row logit-sum) over vocab chunks (online)."""
    vocab = wc.shape[-1]
    w3 = _fhce_w3(wc, chunk, n_chunks, vocab)
    n = x2.shape[0]

    def body(i, carry):
        return _fhce_lse_chunk(x2, w3, i, chunk, vocab, lab, carry)

    m0 = jnp.full((n,), -jnp.inf, jnp.float32)
    zeros = jnp.zeros((n,), jnp.float32)
    m, s, ll, rs = jax.lax.fori_loop(0, n_chunks, body,
                                     (m0, zeros, zeros, zeros))
    return m + jnp.log(s), ll, rs


@register_op("fused_head_cross_entropy", grad_fn=_fused_head_ce_grad,
             grad_fn_is_optimization=True)
def fused_head_cross_entropy(attrs, ins):
    """LM-head projection + softmax cross-entropy WITHOUT materializing
    the [tokens, vocab] logits tensor (beyond-reference; the reference's
    softmax_with_cross_entropy_op.cc predates 100k-token vocabularies).
    Scans the vocab in chunks with an online logsumexp, so peak memory is
    O(tokens * chunk) and the full logits never touch HBM — the TPU-native
    answer to large-vocab heads, where a [16k tokens, 128k vocab] logits
    tensor alone would be 4 GB bf16 (plus its gradient). The chunked
    backward recomputes each chunk and contracts it immediately into
    dX/dW (see _fused_head_ce_grad). Hard labels only.

    X [.., d] x W [d, vocab] + Label [.., 1] -> Loss [.., 1]; also emits
    LSE [..] as a tiny auxiliary residual for the backward."""
    x = single(ins, "X")
    w = single(ins, "W")
    label = single(ins, "Label")
    if attrs.get("soft_label", False):
        raise NotImplementedError(
            "fused_head_cross_entropy supports hard labels only")
    xc, wc = amp_cast(x, w)
    lead = x.shape[:-1]
    d = x.shape[-1]
    vocab = w.shape[-1]
    n = int(np.prod(lead))
    x2 = xc.reshape(n, d)
    lab = label.reshape(n).astype(jnp.int32)
    raw_chunk = attrs.get("chunk", 8192)
    eps = attrs.get("label_smoothing", 0.0)
    mesh = _fhce_vp_mesh(attrs)
    if mesh is not None:
        from ..parallel.vocab_parallel_loss import vp_fused_head_lse

        lse, ll, rs = vp_fused_head_lse(
            x2, wc, lab, raw_chunk, mesh,
            attrs.get("model_axis", "mp"), attrs.get("data_axis", "dp"))
    else:
        chunk, n_chunks = _fhce_chunks(vocab, raw_chunk)
        lse, ll, rs = _fhce_lse(x2, wc, lab, chunk, n_chunks)
    loss = lse - ll
    if eps:
        # target (1-eps)*onehot + eps/V: loss = lse - (1-eps)*x_label
        #   - (eps/V)*sum(x)
        loss = (1.0 - eps) * (lse - ll) + eps * (lse - rs / vocab)
    loss = loss.reshape(lead + (1,))
    return {"Loss": [loss], "LSE": [lse.reshape(lead)]}


@register_op("square_error_cost")
def square_error_cost(attrs, ins):
    x = single(ins, "X")
    y = single(ins, "Y")
    return out(Out=jnp.square(x - y))


@register_op("squared_l2_distance")
def squared_l2_distance(attrs, ins):
    x = single(ins, "X")
    y = single(ins, "Y")
    diff = x - y
    return {"sub_result": [diff],
            "Out": [jnp.sum(jnp.square(diff), axis=-1, keepdims=True)]}


@register_op("squared_l2_norm")
def squared_l2_norm(attrs, ins):
    x = single(ins, "X")
    return out(Out=jnp.sum(jnp.square(x)).reshape(1))


@register_op("hinge_loss")
def hinge_loss(attrs, ins):
    logits = single(ins, "Logits")
    labels = single(ins, "Labels").astype(logits.dtype)
    signs = 2.0 * labels - 1.0
    return out(Loss=jnp.maximum(0.0, 1.0 - signs * logits))


@register_op("huber_loss")
def huber_loss(attrs, ins):
    x = single(ins, "X")
    y = single(ins, "Y")
    delta = attrs.get("delta", 1.0)
    r = y - x
    ar = jnp.abs(r)
    loss = jnp.where(ar <= delta, 0.5 * r * r, delta * (ar - 0.5 * delta))
    return {"Residual": [r], "Out": [loss]}


@register_op("log_loss")
def log_loss(attrs, ins):
    p = single(ins, "Predicted")
    y = single(ins, "Labels")
    eps = attrs.get("epsilon", 1e-4)
    return out(Loss=-y * jnp.log(p + eps) - (1.0 - y) * jnp.log(1.0 - p + eps))


@register_op("rank_loss")
def rank_loss(attrs, ins):
    label = single(ins, "Label")
    left = single(ins, "Left")
    right = single(ins, "Right")
    d = left - right
    return out(Out=jnp.log1p(jnp.exp(d)) - label * d)


@register_op("margin_rank_loss")
def margin_rank_loss(attrs, ins):
    label = single(ins, "Label")
    x1 = single(ins, "X1")
    x2 = single(ins, "X2")
    margin = attrs.get("margin", 0.0)
    o = jnp.maximum(0.0, -label * (x1 - x2) + margin)
    return {"Out": [o], "Activated": [(o > 0).astype(x1.dtype)]}


@register_op("smooth_l1_loss")
def smooth_l1_loss(attrs, ins):
    x = single(ins, "X")
    y = single(ins, "Y")
    sigma = attrs.get("sigma", 1.0)
    s2 = sigma * sigma
    diff = x - y
    in_w = maybe(ins, "InsideWeight")
    out_w = maybe(ins, "OutsideWeight")
    if in_w is not None:
        diff = diff * in_w
    ad = jnp.abs(diff)
    elem = jnp.where(ad < 1.0 / s2, 0.5 * s2 * diff * diff, ad - 0.5 / s2)
    if out_w is not None:
        elem = elem * out_w
    return {"Diff": [diff], "Out": [jnp.sum(elem, axis=-1, keepdims=True)]}


@register_op("sigmoid_cross_entropy_with_logits")
def sigmoid_cross_entropy_with_logits(attrs, ins):
    x = single(ins, "X")
    label = single(ins, "Label")
    loss = jnp.maximum(x, 0.0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    return out(Out=loss)


@register_op("bce_loss")
def bce_loss(attrs, ins):
    x = single(ins, "X")
    label = single(ins, "Label")
    eps = 1e-12
    return out(Out=-(label * jnp.log(x + eps) + (1 - label) * jnp.log(1 - x + eps)))

"""Op kernel registrations. Importing this package populates the registry."""
from . import (activation_ops, attention_ops, control_flow_ops, crf_ops,
               ctc_ops, detection_ops, fusion_ops, legacy_ops, loss_ops,
               math_ops, metric_ops, moe_ops, nn_ops, optimizer_ops,
               pipeline_ops, rnn_ops, seq2seq_ops, sequence_ops,
               sparse_ops, tail_ops, tensor_ops)  # noqa: F401
from . import extra_ops  # noqa: F401  (last: aliases resolve base kernels)

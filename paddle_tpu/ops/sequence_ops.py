"""Sequence ops over padded [batch, time, ...] tensors + per-row lengths.

TPU-native replacement for the reference's LoD-walking sequence kernels
(/root/reference/paddle/operators/sequence_pool_op.cc, sequence_softmax_op.cc,
sequence_expand_op.cc, sequence_conv_op.cc + math/context_project.h,
sequence_concat_op.cc, row_conv_op.cc, sequence_reshape_op.cc and the legacy
hl_sequence.h kernels). The reference stores variable-length batches as
concatenated rows delimited by LoD offsets (framework/lod_tensor.h:43-58) and
walks them with per-sequence loops; XLA wants static shapes, so here every
sequence tensor is dense-padded to the batch max length and carries an int32
``Length`` companion ([batch]) — the SURVEY.md §5.7 dense+mask design. Masked
reductions compile to single fused reduce ops on TPU instead of per-sequence
scalar loops.

Convention: data X is [batch, T, ...feature], Length is int32 [batch],
positions t >= Length[b] are padding (contents arbitrary; ops ignore them and
produce zeros there).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import register_op
from .common import maybe, out, single


def time_mask(lengths, T, dtype=jnp.float32):
    """[batch, T] mask: 1.0 where t < length, else 0."""
    t = jnp.arange(T, dtype=lengths.dtype)
    return (t[None, :] < lengths[:, None]).astype(dtype)


def _expand_mask(mask, x):
    """Broadcast a [b, T] mask over x's trailing feature dims."""
    return mask.reshape(mask.shape + (1,) * (x.ndim - 2))


@register_op("sequence_pool", optional_inputs=("Length",))
def sequence_pool(attrs, ins):
    x = single(ins, "X")  # [b, T, ...]
    lengths = maybe(ins, "Length")
    ptype = attrs.get("pool_type", "average").lower()
    T = x.shape[1]
    if lengths is None:
        lengths = jnp.full((x.shape[0],), T, dtype=jnp.int32)
    mask = time_mask(lengths, T, x.dtype)
    m = _expand_mask(mask, x)
    denom = jnp.maximum(lengths, 1).astype(x.dtype)
    denom = denom.reshape((-1,) + (1,) * (x.ndim - 2))
    if ptype == "sum":
        y = jnp.sum(x * m, axis=1)
    elif ptype == "average":
        y = jnp.sum(x * m, axis=1) / denom
    elif ptype == "sqrt":
        y = jnp.sum(x * m, axis=1) / jnp.sqrt(denom)
    elif ptype == "max":
        neg = jnp.asarray(jnp.finfo(x.dtype).min if jnp.issubdtype(x.dtype, jnp.floating)
                          else jnp.iinfo(x.dtype).min, x.dtype)
        y = jnp.max(jnp.where(m > 0, x, neg), axis=1)
        # empty sequences pool to 0, matching the reference's zero-fill
        y = jnp.where(lengths.reshape(denom.shape) > 0, y, jnp.zeros_like(y))
    elif ptype == "last":
        idx = jnp.maximum(lengths - 1, 0)
        y = jnp.take_along_axis(
            x, idx.reshape((-1, 1) + (1,) * (x.ndim - 2)), axis=1
        ).squeeze(1)
    elif ptype == "first":
        y = x[:, 0]
    else:
        raise ValueError(f"unknown pool_type {ptype!r}")
    return out(Out=y)


@register_op("sequence_softmax", optional_inputs=("Length",))
def sequence_softmax(attrs, ins):
    x = single(ins, "X")  # [b, T] or [b, T, 1]
    lengths = maybe(ins, "Length")
    squeeze = x.ndim == 3 and x.shape[-1] == 1
    z = x[..., 0] if squeeze else x
    T = z.shape[1]
    if lengths is None:
        mask = jnp.ones(z.shape[:2], z.dtype)
    else:
        mask = time_mask(lengths, T, z.dtype)
    neg = jnp.finfo(z.dtype).min
    z = jnp.where(mask > 0, z, neg)
    y = jax.nn.softmax(z, axis=1) * mask
    if squeeze:
        y = y[..., None]
    return out(Out=y)


@register_op("sequence_expand", optional_inputs=("Length",))
def sequence_expand(attrs, ins):
    """Broadcast per-row vectors across the ref sequence's time axis.

    Reference sequence_expand_op.cc repeats row i of X lod(Y)[i] times; in
    padded form that is a broadcast of X [b, d] to [b, T, d] with padding
    masked to zero (T and the mask come from the reference sequence Y).
    """
    x = single(ins, "X")  # [b, d...]
    y = single(ins, "Y")  # [b, T, ...] provides T
    lengths = maybe(ins, "Length")
    T = y.shape[1]
    expanded = jnp.broadcast_to(x[:, None], (x.shape[0], T) + x.shape[1:])
    if lengths is not None:
        mask = _expand_mask(time_mask(lengths, T, x.dtype), expanded)
        expanded = expanded * mask
    return out(Out=expanded)


@register_op("sequence_reverse", optional_inputs=("Length",))
def sequence_reverse(attrs, ins):
    """Reverse each row's valid prefix, leaving padding in place
    (sequence_reverse semantics; feeds bidirectional RNNs)."""
    x = single(ins, "X")  # [b, T, ...]
    lengths = maybe(ins, "Length")
    T = x.shape[1]
    t = jnp.arange(T, dtype=jnp.int32)
    if lengths is None:
        idx = jnp.broadcast_to(t[::-1][None, :], x.shape[:2])
    else:
        rev = lengths[:, None] - 1 - t[None, :]
        idx = jnp.where(t[None, :] < lengths[:, None], rev, t[None, :])
    idx = idx.reshape(idx.shape + (1,) * (x.ndim - 2))
    return out(Y=jnp.take_along_axis(x, idx, axis=1))


@register_op("sequence_conv", optional_inputs=("Length", "PaddingData"))
def sequence_conv(attrs, ins):
    """Context-window projection + filter matmul.

    Reference sequence_conv_op.cc / operators/math/context_project.h: for each
    timestep, gather [context_start, context_start+context_length) neighbour
    rows (zeros outside the sequence), concatenate features, multiply by
    Filter [ctx_len*d, out]. Padded form: shift-and-concat along time, mask,
    one [b*T, k*d] x [k*d, out] matmul on the MXU.
    """
    x = single(ins, "X")  # [b, T, d]
    filt = single(ins, "Filter")  # [k*d, out]
    lengths = maybe(ins, "Length")
    k = int(attrs.get("contextLength", attrs.get("context_length", 3)))
    start = int(attrs.get("contextStart", attrs.get("context_start", -(k // 2))))
    b, T, d = x.shape
    mask = (time_mask(lengths, T, x.dtype)[..., None]
            if lengths is not None else jnp.ones((b, T, 1), x.dtype))
    xm = x * mask
    cols = []
    for off in range(start, start + k):
        if off < 0:
            shifted = jnp.pad(xm, ((0, 0), (-off, 0), (0, 0)))[:, :T]
        elif off > 0:
            shifted = jnp.pad(xm, ((0, 0), (0, off), (0, 0)))[:, off:]
        else:
            shifted = xm
        cols.append(shifted)
    ctx = jnp.concatenate(cols, axis=-1)  # [b, T, k*d]
    y = jnp.einsum("btc,co->bto", ctx, filt)
    return out(Out=y * mask)


@register_op("row_conv", optional_inputs=("Length",))
def row_conv(attrs, ins):
    """Lookahead row convolution (row_conv_op.cc): out[t] = sum_j w[j]*x[t+j]."""
    x = single(ins, "X")  # [b, T, d]
    w = single(ins, "Filter")  # [future_ctx, d]
    lengths = maybe(ins, "Length")
    b, T, d = x.shape
    k = w.shape[0]
    mask = (time_mask(lengths, T, x.dtype)[..., None]
            if lengths is not None else jnp.ones((b, T, 1), x.dtype))
    xm = x * mask
    y = jnp.zeros_like(x)
    for j in range(k):
        shifted = jnp.pad(xm, ((0, 0), (0, j), (0, 0)))[:, j:] if j else xm
        y = y + shifted * w[j]
    return out(Out=y * mask)


@register_op("sequence_concat", optional_inputs=("Length",))
def sequence_concat(attrs, ins):
    """Concatenate sequences along time per batch row
    (sequence_concat_op.cc with axis=0/level=0 semantics).

    Inputs: X = list of [b, T_i, d] tensors, Length = matching list of [b]
    length vectors. Output: [b, sum(T_i), d] with rows packed back-to-back
    and the summed length vector.
    """
    xs = ins["X"]
    lens = ins.get("Length")
    b = xs[0].shape[0]
    if lens is None or not lens:
        lens = [jnp.full((b,), x.shape[1], jnp.int32) for x in xs]
    total_T = sum(x.shape[1] for x in xs)
    out_len = sum(lens)
    # Build, for every output slot t, (which input, source timestep) by
    # comparing t against the running sum of this row's lengths.
    t_idx = jnp.arange(total_T, dtype=jnp.int32)[None, :]  # [1, total_T]
    starts = []
    acc = jnp.zeros((b, 1), jnp.int32)
    for ln in lens:
        starts.append(acc)
        acc = acc + ln[:, None]
    result = jnp.zeros((b, total_T) + xs[0].shape[2:], xs[0].dtype)
    for x, ln, st in zip(xs, lens, starts):
        Ti = x.shape[1]
        src_t = jnp.clip(t_idx - st, 0, Ti - 1)
        src_t = src_t.reshape(src_t.shape + (1,) * (x.ndim - 2))
        gathered = jnp.take_along_axis(
            jnp.broadcast_to(x, (b,) + x.shape[1:]), src_t, axis=1)
        sel = (t_idx >= st) & (t_idx < st + ln[:, None])
        sel = sel.reshape(sel.shape + (1,) * (x.ndim - 2))
        result = jnp.where(sel, gathered, result)
    return out(Out=result, OutLength=out_len.astype(jnp.int32))


@register_op("sequence_enumerate", optional_inputs=("Length",))
def sequence_enumerate(attrs, ins):
    """Sliding n-gram window over id sequences (sequence_enumerate_op.cc):
    out[b, t] = [ids[t], ids[t+1], ..., ids[t+win-1]], pad_value past end."""
    x = single(ins, "X")  # [b, T] int ids
    lengths = maybe(ins, "Length")
    win = int(attrs.get("win_size", 2))
    pad = attrs.get("pad_value", 0)
    b, T = x.shape[:2]
    if lengths is None:
        lengths = jnp.full((b,), T, jnp.int32)
    cols = []
    for j in range(win):
        shifted = jnp.pad(x, ((0, 0), (0, j)), constant_values=pad)[:, j:]
        valid = (jnp.arange(T, dtype=jnp.int32)[None, :] + j) < lengths[:, None]
        cols.append(jnp.where(valid, shifted, pad))
    return out(Out=jnp.stack(cols, axis=-1))


@register_op("sequence_mask", optional_inputs=("MaxLenRef",))
def sequence_mask(attrs, ins):
    """Lengths -> [b, maxlen] 0/1 mask (sequence_mask semantics).

    maxlen comes from the static ``maxlen`` attr, or — for dynamic-length
    graphs where no static bound exists at build time — from the last dim
    of the optional ``MaxLenRef`` input (concrete once the executor
    compiles against the actual feeds)."""
    lengths = single(ins, "X")
    maxlen = int(attrs.get("maxlen", -1))
    if maxlen <= 0:
        ref = maybe(ins, "MaxLenRef")
        if ref is None:
            raise ValueError(
                "sequence_mask requires a static maxlen attr or a "
                "MaxLenRef input on TPU")
        maxlen = ref.shape[-1]
    dtype = attrs.get("out_dtype", "float32")
    return out(Y=time_mask(lengths, maxlen, jnp.dtype(dtype)))


@register_op("context_project", optional_inputs=("Length",))
def context_project(attrs, ins):
    """Context-window concatenation WITHOUT the filter matmul — the v1
    context_projection (reference trainer_config_helpers/layers.py
    context_projection -> ContextProjection.cpp): each timestep's feature
    row becomes the concat of its [start, start+length) neighbours, zeros
    outside the sequence. The filterless half of sequence_conv above."""
    x = single(ins, "X")  # [b, T, d]
    lengths = maybe(ins, "Length")
    k = int(attrs["context_length"])
    start = int(attrs.get("context_start", -(k // 2)))
    b, T, d = x.shape
    mask = (time_mask(lengths, T, x.dtype)[..., None]
            if lengths is not None else jnp.ones((b, T, 1), x.dtype))
    xm = x * mask
    cols = []
    for off in range(start, start + k):
        if off < 0:
            shifted = jnp.pad(xm, ((0, 0), (-off, 0), (0, 0)))[:, :T]
        elif off > 0:
            shifted = jnp.pad(xm, ((0, 0), (0, off), (0, 0)))[:, off:]
        else:
            shifted = xm
        cols.append(shifted)
    return out(Out=jnp.concatenate(cols, axis=-1) * mask)

"""Fused epilogue ops: 1x1-conv + BN + activation (+ residual) as ONE op.

TPU-first replacement for the reference's separate cudnn-conv + BN +
eltwise kernel sequence (/root/reference/paddle/operators/
conv_cudnn_op.cu.cc, batch_norm_op.cc, elementwise_add_op.cc): the
ResNet roofline (PERF.md) is HBM-bound and the byte cut comes from not
materializing intermediates between the conv dot and its epilogue. The
forward runs the Pallas kernels in kernels/conv_epilogue.py; the
backward is plain XLA (the fused-backward tombstone in PERF.md is why).

Only the NHWC 1x1/stride-1/pad-0 form exists — exactly the layers the
roofline names. The model layer (models/resnet.py _conv_bn) falls back
to the separate conv2d/batch_norm/elementwise_add ops for every other
shape, and when --fused_conv_epilogue is off (the default until the
chip A/B lands).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op
from . import common
from .common import maybe, out, single


def _affine_from_stats(scale, bias, mean, var, eps):
    """Fold (gamma, beta, mean, var) into the elementwise (k, b):
    y = xhat*gamma + beta = x*k + b with k = gamma*rsqrt(var+eps)."""
    inv = jax.lax.rsqrt(var.astype(jnp.float32) + eps)
    k = scale.astype(jnp.float32) * inv
    b = bias.astype(jnp.float32) - mean.astype(jnp.float32) * k
    return k, b, inv


def conv1x1_bn_act(attrs, ins):
    """Fused y = act(BN(x @ W) [+ residual]) for NHWC 1x1 convs.

    Training: one Pallas pass computes the conv output AND the BN batch
    statistics; a second elementwise pass applies the folded affine,
    residual and activation. ConvOut (the raw conv output) is a real
    output so the backward reads it instead of recomputing the dot.
    Inference: single pass, raw conv output never reaches HBM.
    Output contract mirrors batch_norm (MeanOut/VarianceOut alias the
    running stats; SavedMean/SavedVariance are batch mean / inv-std).
    """
    from ..kernels import conv_epilogue as ke

    x = single(ins, "X")            # [B, H, W, I]
    w = single(ins, "Filter")       # [1, 1, I, O] (HWIO) or [I, O]
    scale = single(ins, "Scale")
    bias = single(ins, "Bias")
    mean = single(ins, "Mean")
    var = single(ins, "Variance")
    res = maybe(ins, "Residual")
    eps = attrs.get("epsilon", 1e-5)
    momentum = attrs.get("momentum", 0.9)
    act = attrs.get("act") or None
    is_test = attrs.get("is_test", False)
    interpret = jax.default_backend() != "tpu"

    B, H, W_, I = x.shape
    wm = w.reshape(w.shape[-2], w.shape[-1])
    O = wm.shape[-1]
    x2, wm = common.amp_cast(x.reshape(B * H * W_, I), wm)
    res2 = None if res is None else res.reshape(B * H * W_, O)
    prec = common.mxu_precision()

    if is_test:
        k, b, inv = _affine_from_stats(scale, bias, mean, var, eps)
        y2 = ke.conv1x1_epilogue(x2, wm, k, b, residual=res2, act=act,
                                 precision=prec, interpret=interpret)
        return out(Y=y2.reshape(B, H, W_, O).astype(x.dtype),
                   MeanOut=mean, VarianceOut=var, SavedMean=mean,
                   SavedVariance=jax.lax.rsqrt(
                       var.astype(jnp.float32) + eps).astype(var.dtype),
                   ConvOut=jnp.zeros((1, 1), x.dtype))

    y_raw2, stats = ke.conv1x1_stats(x2, wm, precision=prec,
                                     interpret=interpret)
    n = x2.shape[0]
    bmean = stats[0] / n
    bvar = jnp.maximum(stats[1] / n - jnp.square(bmean), 0.0)
    k, b, inv = _affine_from_stats(scale, bias, bmean, bvar, eps)
    y2 = ke.scale_shift_act(y_raw2, k, b, residual=res2, act=act,
                            interpret=interpret)
    mean_out = momentum * mean.astype(jnp.float32) + (1 - momentum) * bmean
    var_out = momentum * var.astype(jnp.float32) + (1 - momentum) * bvar
    return out(Y=y2.reshape(B, H, W_, O).astype(x.dtype),
               MeanOut=mean_out.astype(mean.dtype),
               VarianceOut=var_out.astype(var.dtype),
               SavedMean=bmean.astype(mean.dtype),
               SavedVariance=inv.astype(var.dtype),
               ConvOut=y_raw2.reshape(B, H, W_, O))


def _conv1x1_bn_act_grad(attrs, ins, outs, ogs):
    """XLA backward for the fused op: relu mask -> BN backward over the
    saved raw conv output -> the two gradient dots (reference
    mul_op.cc backward structure)."""
    x = single(ins, "X")
    w = single(ins, "Filter")
    scale = single(ins, "Scale")
    res = maybe(ins, "Residual")
    eps = attrs.get("epsilon", 1e-5)
    momentum = attrs.get("momentum", 0.9)
    act = attrs.get("act") or None
    is_test = attrs.get("is_test", False)

    dy = ogs.get("Y", [None])[0]
    gm = ogs.get("MeanOut", [None])[0]
    gv = ogs.get("VarianceOut", [None])[0]
    y = outs.get("Y", [None])[0]
    if dy is None:
        raise NotImplementedError("conv1x1_bn_act grad needs dY")

    B, H, W_, I = x.shape
    wm = w.reshape(w.shape[-2], w.shape[-1])
    O = wm.shape[-1]
    n = B * H * W_
    x2 = x.reshape(n, I)
    dy2 = dy.reshape(n, O).astype(jnp.float32)
    if act == "relu":
        dy2 = dy2 * (y.reshape(n, O) > 0)
    dres = None if res is None else dy2.astype(res.dtype).reshape(res.shape)

    sm = outs["SavedMean"][0].astype(jnp.float32)
    inv = outs["SavedVariance"][0].astype(jnp.float32)
    sc = scale.astype(jnp.float32)
    prec = common.mxu_precision()
    if is_test:
        # the inference forward never materialized the raw conv output
        # (that is its point) — recompute it for the scale/bias grads
        x2c_, wmc_ = common.amp_cast(x2, wm)
        y_raw2 = jax.lax.dot_general(
            x2c_, wmc_, (((1,), (0,)), ((), ())), precision=prec,
            preferred_element_type=jnp.float32)
        dz = dy2 * (sc * inv)
        xhat = (y_raw2 - sm) * inv
        dscale = jnp.sum(dy2 * xhat, axis=0)
        dbias = jnp.sum(dy2, axis=0)
    else:
        y_raw2 = outs["ConvOut"][0].reshape(n, O).astype(jnp.float32)
        xhat = (y_raw2 - sm) * inv
        dbias = jnp.sum(dy2, axis=0)
        dscale = jnp.sum(dy2 * xhat, axis=0)
        dz = (sc * inv) * (dy2 - (dbias + xhat * dscale) / n)
        # running-stat update cotangents flow into y_raw through the
        # batch statistics, and into the Mean/Variance state inputs
        if gm is not None:
            dz = dz + ((1.0 - momentum) / n) * gm.astype(jnp.float32)
        if gv is not None:
            dz = dz + ((1.0 - momentum) * 2.0 / n) \
                * gv.astype(jnp.float32) * (y_raw2 - sm)
    x2c, dzc = common.amp_cast(x2, dz.astype(x.dtype))
    wmc = common.amp_cast(wm)
    dx2 = jax.lax.dot_general(dzc, wmc, (((1,), (1,)), ((), ())),
                              precision=prec)
    dw2 = jax.lax.dot_general(x2c, dzc, (((0,), (0,)), ((), ())),
                              precision=prec)
    grads = {"X": [dx2.reshape(x.shape).astype(x.dtype)],
             "Filter": [dw2.reshape(w.shape).astype(w.dtype)],
             "Scale": [dscale.astype(scale.dtype)],
             "Bias": [dbias.astype(scale.dtype)]}
    if dres is not None:
        grads["Residual"] = [dres]
    if not is_test:
        mean_in = single(ins, "Mean")
        var_in = single(ins, "Variance")
        if gm is not None:
            grads["Mean"] = [(momentum * gm.astype(jnp.float32))
                             .astype(mean_in.dtype)]
        if gv is not None:
            grads["Variance"] = [(momentum * gv.astype(jnp.float32))
                                 .astype(var_in.dtype)]
    return grads


register_op("conv1x1_bn_act", conv1x1_bn_act,
            grad_fn=_conv1x1_bn_act_grad,
            optional_inputs=("Residual",))

"""Attention ops: fused scaled-dot-product attention.

No counterpart exists in the reference (it predates Transformers —
SURVEY.md §5.7); this is the capability-extension tier. The kernel routes to
the Pallas flash-attention kernel on TPU (kernels/flash_attention.py) and a
fused-by-XLA jnp reference elsewhere; gradients come from the op's
custom_vjp (recompute), so the generic backward works unchanged.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.registry import register_op
from ..kernels.flash_attention import flash_attention
from .common import maybe, out, single


@register_op("scaled_dot_product_attention", optional_inputs=("Length",))
def scaled_dot_product_attention(attrs, ins):
    """Q/K/V [B, H, T, D] -> [B, H, T, D]. attrs: causal, sm_scale,
    sequence_parallel (use ring attention over the mesh's 'sp' axis when the
    executor compiles with a mesh that has one — the long-context path)."""
    from ..parallel.context import current_mesh, mesh_axis

    q = single(ins, "Q")
    k = single(ins, "K")
    v = single(ins, "V")
    lengths = maybe(ins, "Length")
    causal = attrs.get("causal", False)
    if attrs.get("sequence_parallel", False) and mesh_axis("sp") > 1:
        if lengths is not None:
            raise NotImplementedError(
                "ring attention path assumes full-length sequences; pad-free "
                "batches should use the single-chip flash path")
        from ..parallel.ring_attention import ring_attention

        y = ring_attention(q, k, v, current_mesh(), seq_axis="sp",
                           causal=causal, sm_scale=attrs.get("sm_scale"))
        return out(Out=y)
    y = flash_attention(q, k, v, lengths=lengths, causal=causal,
                        sm_scale=attrs.get("sm_scale"))
    return out(Out=y)

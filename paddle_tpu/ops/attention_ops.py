"""Attention ops: fused scaled-dot-product attention.

No counterpart exists in the reference (it predates Transformers —
SURVEY.md §5.7); this is the capability-extension tier. The kernel routes to
the Pallas flash-attention kernel on TPU (kernels/flash_attention.py) and a
fused-by-XLA jnp reference elsewhere; gradients come from the op's
custom_vjp (recompute), so the generic backward works unchanged.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.registry import register_op
from ..kernels.flash_attention import flash_attention
from .common import maybe, out, single


@register_op("rotary_embed")
def rotary_embed(attrs, ins):
    """Rotary position embedding over [B, H, T, D] heads (RoFormer; the
    modern relative-position scheme for long-context LMs). Purely a
    function of position, so it lives in-graph with no table parameter;
    the math is kernels.flash_attention.rotary (shared with the stacked
    stack and incremental decode)."""
    from ..kernels.flash_attention import rotary

    x = single(ins, "X")
    return out(Out=rotary(x, base=attrs.get("base", 10000.0)))


@register_op("scaled_dot_product_attention", optional_inputs=("Length",))
def scaled_dot_product_attention(attrs, ins):
    """Q [B, H, T, D], K/V [B, Hkv, T, D] -> [B, H, T, D]. attrs: causal,
    sm_scale, sequence_parallel (use ring attention over the mesh's 'sp'
    axis when the executor compiles with a mesh that has one — the
    long-context path). Hkv may divide H (grouped-query / multi-query
    attention): K/V heads are broadcast to their query groups."""
    from ..parallel.context import current_mesh, mesh_axis

    q = single(ins, "Q")
    k = single(ins, "K")
    v = single(ins, "V")
    if k.shape[1] != q.shape[1]:
        if q.shape[1] % k.shape[1]:
            raise ValueError(
                f"query heads {q.shape[1]} not a multiple of kv heads "
                f"{k.shape[1]}")
        rep = q.shape[1] // k.shape[1]
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    lengths = maybe(ins, "Length")
    causal = attrs.get("causal", False)
    if attrs.get("sequence_parallel", False) and mesh_axis("sp") > 1:
        if lengths is not None:
            raise NotImplementedError(
                "ring attention path assumes full-length sequences; pad-free "
                "batches should use the single-chip flash path")
        from ..parallel.ring_attention import ring_attention

        y = ring_attention(q, k, v, current_mesh(), seq_axis="sp",
                           causal=causal, sm_scale=attrs.get("sm_scale"))
        return out(Out=y)
    y = flash_attention(q, k, v, lengths=lengths, causal=causal,
                        sm_scale=attrs.get("sm_scale"))
    return out(Out=y)

"""Mixture-of-Experts op: Switch-style top-1 routing with capacity.

Capability extension beyond the reference (no MoE exists there; the closest
analogue is the sparse-parameter pserver path this replaces — SelectedRows
updates touching only some rows, /root/reference/paddle/framework/
selected_rows.h). Expert-parallel scaling: the expert-major weight tensors
[E, ...] shard their leading dim over the mesh's 'ep' axis, so each device
holds E/n experts and the dispatch/combine einsums become all-to-alls that
XLA GSPMD inserts — the TPU-native version of what a CUDA framework builds
from NCCL all-to-all.

Formulation (Switch Transformer): token -> top-1 expert via gate softmax;
per-expert capacity C = ceil(tokens/E * capacity_factor); tokens beyond an
expert's capacity are dropped (pass through the residual); dispatch and
combine are one-hot einsums, keeping everything dense/static for XLA.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op
from .common import amp_cast, mxu_precision, out, single


@register_op("switch_moe", optional_inputs=("GateBias",))
def switch_moe(attrs, ins):
    """X [b, T, d]; Gate [d, E]; W1 [E, d, ff]; B1 [E, ff]; W2 [E, ff, d];
    B2 [E, d] -> Out [b, T, d] plus AuxLoss [1] (load-balance loss)."""
    x = single(ins, "X")
    wg = single(ins, "Gate")
    w1 = single(ins, "W1")
    b1 = single(ins, "B1")
    w2 = single(ins, "W2")
    b2 = single(ins, "B2")
    capacity_factor = attrs.get("capacity_factor", 1.25)
    b, T, d = x.shape
    E = wg.shape[1]
    n_tok = b * T
    cap = int(max(1, round(n_tok / E * capacity_factor)))

    xt = x.reshape(n_tok, d)
    logits = jnp.dot(xt, wg, precision=mxu_precision()).astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)  # [N, E]
    expert = jnp.argmax(probs, axis=-1)  # [N]
    gate = jnp.max(probs, axis=-1)  # [N] routing weight

    # position of each token within its expert's queue (0-based)
    onehot = jax.nn.one_hot(expert, E, dtype=jnp.int32)  # [N, E]
    pos_in_expert = jnp.cumsum(onehot, axis=0) * onehot  # 1-based at slot
    pos = jnp.sum(pos_in_expert, axis=-1) - 1  # [N]
    keep = pos < cap

    # dispatch one-hot [N, E, C]
    dispatch = (jax.nn.one_hot(expert, E, dtype=x.dtype)[:, :, None]
                * jax.nn.one_hot(jnp.where(keep, pos, cap), cap + 1,
                                 dtype=x.dtype)[:, None, :cap])
    xe = jnp.einsum("nec,nd->ecd", dispatch, xt)  # [E, C, d]
    xe_c, w1_c = amp_cast(xe, w1)
    h = jax.nn.gelu(
        jnp.einsum("ecd,edf->ecf", xe_c, w1_c,
                   precision=mxu_precision()).astype(xe.dtype)
        + b1[:, None, :])
    h_c, w2_c = amp_cast(h, w2)
    ye = jnp.einsum("ecf,efd->ecd", h_c, w2_c,
                    precision=mxu_precision()).astype(xe.dtype) \
        + b2[:, None, :]
    combine = dispatch * gate[:, None, None].astype(x.dtype)
    y = jnp.einsum("nec,ecd->nd", combine, ye)  # dropped tokens -> 0

    # Switch load-balance auxiliary loss: E * sum_e f_e * p_e
    frac_tokens = jnp.mean(onehot.astype(jnp.float32), axis=0)
    frac_probs = jnp.mean(probs, axis=0)
    aux = E * jnp.sum(frac_tokens * frac_probs)
    return out(Out=y.reshape(b, T, d).astype(x.dtype),
               AuxLoss=aux.reshape(1))

"""Encoder-decoder (seq2seq / NMT) ops on the paged decode plane.

The encoder-decoder split maps cleanly onto serving phases: the ENCODER
runs exactly once per request (admission time), so its product — the
per-layer cross-attention K/V of the source sentence — is computed once
and parked in a slot-resident cache ``[L, S+1, Hkv, Ts, dh]`` alongside
the self-attention page pool (row ``S`` is the scrap row padding and
vacant slots address). The DECODER is the familiar paged continuous-
batching loop plus one cross-attention block per layer that READS the
parked rows; decode never re-touches the encoder. Because the cross
cache is read-only after admission, a beam fork shares its parent's
cross row by refcount — K hypotheses of one translation carry ONE copy
of the source K/V.

Weight layout: the decoder reuses the stacked-LM contract (tok_emb /
pos_emb / lm_stack.* / final_ln.* / lm_head.w — the target-side "LM")
extended with per-layer cross weights (ln/q/out projections, slots
XLnS/XLnB/XQW/XOutW), while the encoder carries its own stack
(enc_stack.*, src_emb, src_pos_emb, enc_ln.*) plus the cross K/V
projection ``xattn.stack_kv_w [L, d, 2·Hkv·dh]`` applied to the encoder
memory at encode time.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op
from .common import maybe, out, single
from .pipeline_ops import (_SAMPLING_SLOTS, _STACK_SLOTS, _attn_out_ffn,
                           _attn_proj, _expand_kv, _gather_pages,
                           _logits_fn, _ln, _maybe_topk, _pick_rows)

# encoder stack slots: the same 10-weight block layout, Enc-prefixed
_ENC_SLOTS = {f"Enc{slot}": key for slot, key in _STACK_SLOTS.items()}
# decoder cross-attention slots (per-layer, stacked [L, ...])
_CROSS_SLOTS = ("XLnS", "XLnB", "XQW", "XOutW")


def _unpack_cross(ins):
    return {k.lower(): single(ins, k) for k in _CROSS_SLOTS}


def _cross_attend(h1, xw, ck_x, cv_x, src_len, num_heads):
    """One-token (or window) cross-attention block: pre-LN query
    projection against the parked encoder K/V rows. h1 [b, t, d];
    ck_x/cv_x [b, Hkv, Ts, dh]; src_len [b]."""
    from ..kernels.flash_attention import reference_attention

    b, t, d = h1.shape
    head_d = d // num_heads
    hx = _ln(h1, xw["xlns"], xw["xlnb"])
    q = jnp.einsum("btd,de->bte", hx, xw["xqw"])
    q = q.reshape(b, t, num_heads, head_d).transpose(0, 2, 1, 3)
    ctx = reference_attention(q, ck_x, cv_x, lengths=src_len)
    ctx = ctx.transpose(0, 2, 1, 3).reshape(b, t, d)
    return h1 + jnp.einsum("btd,de->bte", ctx, xw["xoutw"])


def _encode_memory(ins, attrs, src, src_len):
    """Shared encoder forward: embedded source through the Enc stack
    (bidirectional, length-masked) + final LN -> memory [b, Ts, d]."""
    from ..kernels.flash_attention import reference_attention

    params = {key: single(ins, slot) for slot, key in _ENC_SLOTS.items()}
    tok_emb = single(ins, "SrcTokEmb")
    pos_emb = maybe(ins, "SrcPosEmb")
    num_heads = attrs["num_heads"]
    num_kv_heads = attrs.get("num_kv_heads") or num_heads
    b, Ts = src.shape
    d = params["ln1_s"].shape[1]
    x = tok_emb[src]
    if pos_emb is not None:
        x = x + pos_emb[None, :Ts]

    def block(h, layer_p):
        q, k, v = _attn_proj(layer_p, h, num_heads, num_kv_heads)
        kx, vx = _expand_kv(k, v, num_heads)
        ctx = reference_attention(q, kx, vx, lengths=src_len)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(b, Ts, d)
        return _attn_out_ffn(layer_p, h, ctx), None

    h, _ = jax.lax.scan(block, x, params)
    return _ln(h, single(ins, "EncLnS"), single(ins, "EncLnB"))


def _project_cross_kv(memory, xkv_w, num_kv_heads):
    """memory [b, Ts, d] x xkv_w [L, d, 2·Hkv·dh] -> per-layer cross
    K/V [L, b, Hkv, Ts, dh]."""
    b, Ts, d = memory.shape
    L = xkv_w.shape[0]
    d_kv = xkv_w.shape[2] // 2
    dh = d_kv // num_kv_heads
    kv = jnp.einsum("btd,lde->lbte", memory, xkv_w)
    k, v = kv[..., :d_kv], kv[..., d_kv:]

    def heads(a):
        return a.reshape(L, b, Ts, num_kv_heads, dh).transpose(
            0, 1, 3, 2, 4)

    return heads(k), heads(v)


@register_op("transformer_encdec_encode", optional_inputs=("SrcPosEmb",))
def transformer_encdec_encode(attrs, ins):
    """Run the encoder ONCE for a batch of admitted sources and park
    their cross-attention K/V in the slot cache.

    SrcIds [b, Ts] int (right-padded), SrcLen [b] int32, SlotIds [b]
    int32 (cross-cache row per request; padding rows target the scrap
    row S), SrcTokEmb [Vs, d], SrcPosEmb [Tsmax, d] (optional), the
    Enc-prefixed stacked encoder weights + EncLnS/EncLnB [d], XKvW
    [L, d, 2·Hkv·dh] (the DECODER's cross K/V projection — applied here
    so decode never touches the encoder memory), CrossK/CrossV
    [L, S+1, Hkv, Tsmax, dh]. Returns Ok [b] (echoed slot ids — the
    fetchable witness) and the cross caches with rows 0..Ts-1 of each
    target row overwritten (donated in place).
    """
    src = single(ins, "SrcIds")
    src_len = single(ins, "SrcLen").astype(jnp.int32)
    slot_ids = single(ins, "SlotIds").astype(jnp.int32)
    cross_k = single(ins, "CrossK")
    cross_v = single(ins, "CrossV")
    num_heads = attrs["num_heads"]
    num_kv_heads = attrs.get("num_kv_heads") or num_heads
    Ts = src.shape[1]
    if Ts > cross_k.shape[3]:
        raise ValueError(f"source bucket {Ts} exceeds the cross cache "
                         f"length {cross_k.shape[3]}")
    memory = _encode_memory(ins, attrs, src, src_len)
    k, v = _project_cross_kv(memory, single(ins, "XKvW"), num_kv_heads)
    # [L, b, Hkv, Ts, dh] -> scatter rows into their slots
    cross_k = cross_k.at[:, slot_ids, :, :Ts, :].set(k)
    cross_v = cross_v.at[:, slot_ids, :, :Ts, :].set(v)
    return out(Ok=slot_ids, CrossK=cross_k, CrossV=cross_v)


@register_op("transformer_stack_cross_prefill",
             optional_inputs=("PosEmb",) + _SAMPLING_SLOTS)
def transformer_stack_cross_prefill(attrs, ins, rng=None):
    """Paged chunk prefill of the TARGET prefix with cross-attention.

    The paged-prefill contract (Chunk/StartPos/Lengths/BlockTable +
    CacheK/CacheV page pools + the stacked-LM decoder weights) extended
    per layer with a cross-attention block over the parked encoder rows:
    XSlot [b] int32 (each row's cross-cache row), SrcLen [b] int32,
    CrossK/CrossV [L, S+1, Hkv, Tsmax, dh] (read-only here), and the
    XLnS/XLnB/XQW/XOutW stacked cross weights. Per-row sampling plane
    and ``emit_topk`` behave exactly like transformer_stack_paged_prefill.
    """
    # per-row sampling slots, read via _row_sampling/_maybe_topk:
    # "Temperature", "TopK", "TopP", "Seed", "Step", "Mask"
    chunk = single(ins, "Chunk")
    start = single(ins, "StartPos").astype(jnp.int32)
    lengths = single(ins, "Lengths").astype(jnp.int32)
    table = single(ins, "BlockTable").astype(jnp.int32)
    xslot = single(ins, "XSlot").astype(jnp.int32)
    src_len = single(ins, "SrcLen").astype(jnp.int32)
    cache_k, cache_v = single(ins, "CacheK"), single(ins, "CacheV")
    cross_k, cross_v = single(ins, "CrossK"), single(ins, "CrossV")
    tok_emb = single(ins, "TokEmb")
    pos_emb = maybe(ins, "PosEmb")
    ln_s, ln_b = single(ins, "FinalLnS"), single(ins, "FinalLnB")
    head_w = single(ins, "HeadW")
    params = {key: single(ins, slot) for slot, key in _STACK_SLOTS.items()}
    xparams = _unpack_cross(ins)
    num_heads = attrs["num_heads"]
    num_kv_heads = attrs.get("num_kv_heads") or num_heads
    b, Tc = chunk.shape
    ps = cache_k.shape[3]
    P = table.shape[1]
    d = params["ln1_s"].shape[1]
    pos = start[:, None] + jnp.arange(Tc, dtype=jnp.int32)[None, :]
    valid = jnp.arange(Tc, dtype=jnp.int32)[None, :] < lengths[:, None]
    entry = jnp.clip(pos // ps, 0, P - 1)
    page_id = jnp.where(
        valid, jnp.take_along_axis(table, entry, axis=1), 0)
    page_row = jnp.where(valid, pos % ps, 0)
    x = tok_emb[chunk]
    if pos_emb is not None:
        x = x + pos_emb[jnp.clip(pos, 0, pos_emb.shape[0] - 1)]
    from ..kernels.flash_attention import reference_attention

    def layer(h, inp):
        (layer_p, ck_l, cv_l, xk_l, xv_l, xlns, xlnb, xqw, xoutw) = inp
        xw = {"xlns": xlns, "xlnb": xlnb, "xqw": xqw, "xoutw": xoutw}
        q, k, v = _attn_proj(layer_p, h, num_heads, num_kv_heads,
                             pos0=start)
        ck_l = ck_l.at[page_id, :, page_row, :].set(k.transpose(0, 2, 1, 3))
        cv_l = cv_l.at[page_id, :, page_row, :].set(v.transpose(0, 2, 1, 3))
        ctx = reference_attention(q, _gather_pages(ck_l, table),
                                  _gather_pages(cv_l, table),
                                  causal=True, q_pos0=start)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(b, Tc, d)
        # self-attn residual, then cross block, then FFN
        h = h + jnp.einsum("btd,de->bte", ctx, layer_p["out_w"])
        h = _cross_attend(h, xw, xk_l[xslot], xv_l[xslot], src_len,
                          num_heads)
        h2 = _ln(h, layer_p["ln2_s"], layer_p["ln2_b"])
        ff = jax.nn.gelu(jnp.einsum("btd,df->btf", h2, layer_p["ff_w1"])
                         + layer_p["ff_b1"])
        h = h + jnp.einsum("btf,fd->btd", ff, layer_p["ff_w2"]) \
            + layer_p["ff_b2"]
        return h, (ck_l, cv_l)

    h, (cache_k, cache_v) = jax.lax.scan(
        layer, x,
        (params, cache_k, cache_v, cross_k, cross_v,
         xparams["xlns"], xparams["xlnb"], xparams["xqw"],
         xparams["xoutw"]))
    last = h[jnp.arange(b), jnp.clip(lengths, 1, Tc) - 1]
    logits = _logits_fn(ln_s, ln_b, head_w)(last)
    nxt = _pick_rows(attrs, ins, rng, head_w.shape[1], logits)
    outs = out(NextTok=nxt.astype(chunk.dtype),
               CacheK=cache_k, CacheV=cache_v)
    return _maybe_topk(attrs, ins, logits, outs)


@register_op("transformer_stack_cross_decode",
             optional_inputs=("PosEmb",) + _SAMPLING_SLOTS)
def transformer_stack_cross_decode(attrs, ins, rng=None):
    """One decode step over every slot's paged target context PLUS a
    cross-attention read of its parked encoder rows.

    The transformer_stack_paged_decode contract extended with XSlot [S]
    int32 (cross-cache row per slot; vacant slots address the scrap
    row), SrcLen [S] int32, CrossK/CrossV [L, S+1, Hkv, Tsmax, dh]
    (READ-ONLY — written once by transformer_encdec_encode), and the
    stacked cross weights. Same per-row sampling plane and ``emit_topk``
    beam plane; same one-compile steady state.
    """
    # per-row sampling slots, read via _row_sampling/_maybe_topk:
    # "Temperature", "TopK", "TopP", "Seed", "Step", "Mask"
    tok = single(ins, "Tok")
    pos = single(ins, "Pos").astype(jnp.int32)
    table = single(ins, "BlockTable").astype(jnp.int32)
    xslot = single(ins, "XSlot").astype(jnp.int32)
    src_len = single(ins, "SrcLen").astype(jnp.int32)
    cache_k, cache_v = single(ins, "CacheK"), single(ins, "CacheV")
    cross_k, cross_v = single(ins, "CrossK"), single(ins, "CrossV")
    tok_emb = single(ins, "TokEmb")
    pos_emb = maybe(ins, "PosEmb")
    ln_s, ln_b = single(ins, "FinalLnS"), single(ins, "FinalLnB")
    head_w = single(ins, "HeadW")
    params = {key: single(ins, slot) for slot, key in _STACK_SLOTS.items()}
    xparams = _unpack_cross(ins)
    num_heads = attrs["num_heads"]
    num_kv_heads = attrs.get("num_kv_heads") or num_heads
    S = tok.shape[0]
    ps = cache_k.shape[3]
    P = table.shape[1]
    d = params["ln1_s"].shape[1]
    pos = jnp.clip(pos, 0, P * ps - 1)
    x = tok_emb[tok]
    if pos_emb is not None:
        x = x + pos_emb[jnp.clip(pos, 0, pos_emb.shape[0] - 1)]
    h1 = x[:, None, :]
    srange = jnp.arange(S)
    page_id = table[srange, pos // ps]
    page_row = pos % ps
    from ..kernels.flash_attention import reference_attention

    def layer(h1, inp):
        (layer_p, ck_l, cv_l, xk_l, xv_l, xlns, xlnb, xqw, xoutw) = inp
        xw = {"xlns": xlns, "xlnb": xlnb, "xqw": xqw, "xoutw": xoutw}
        q, k, v = _attn_proj(layer_p, h1, num_heads, num_kv_heads,
                             pos0=pos)
        ck_l = ck_l.at[page_id, :, page_row, :].set(k[:, :, 0, :])
        cv_l = cv_l.at[page_id, :, page_row, :].set(v[:, :, 0, :])
        ctx = reference_attention(q, _gather_pages(ck_l, table),
                                  _gather_pages(cv_l, table),
                                  lengths=pos + 1)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(S, 1, d)
        h = h1 + jnp.einsum("btd,de->bte", ctx, layer_p["out_w"])
        h = _cross_attend(h, xw, xk_l[xslot], xv_l[xslot], src_len,
                          num_heads)
        h2 = _ln(h, layer_p["ln2_s"], layer_p["ln2_b"])
        ff = jax.nn.gelu(jnp.einsum("btd,df->btf", h2, layer_p["ff_w1"])
                         + layer_p["ff_b1"])
        h = h + jnp.einsum("btf,fd->btd", ff, layer_p["ff_w2"]) \
            + layer_p["ff_b2"]
        return h, (ck_l, cv_l)

    h1, (cache_k, cache_v) = jax.lax.scan(
        layer, h1,
        (params, cache_k, cache_v, cross_k, cross_v,
         xparams["xlns"], xparams["xlnb"], xparams["xqw"],
         xparams["xoutw"]))
    logits = _logits_fn(ln_s, ln_b, head_w)(h1[:, 0])
    nxt = _pick_rows(attrs, ins, rng, head_w.shape[1], logits)
    outs = out(NextTok=nxt.astype(tok.dtype),
               CacheK=cache_k, CacheV=cache_v)
    return _maybe_topk(attrs, ins, logits, outs)


@register_op("transformer_encdec_teacher",
             optional_inputs=("SrcPosEmb", "PosEmb"))
def transformer_encdec_teacher(attrs, ins):
    """Teacher-forced encoder-decoder forward: the NMT TRAINING (and
    reference-decode) path.

    SrcIds [b, Ts] + SrcLen [b] + the encoder/cross inputs of
    transformer_encdec_encode, TgtIn [b, Tt] + the stacked-LM decoder
    weights + cross weights -> Logits [b, Tt, V]: decoder position t
    attends target positions <= t (causal) and every valid source
    position (cross). Differentiable end to end through the generic
    grad machinery — this op IS the training graph; the paged
    cross-decode ops serve what it learns, token-exact.
    """
    from ..kernels.flash_attention import flash_attention

    src = single(ins, "SrcIds")
    src_len = single(ins, "SrcLen").astype(jnp.int32)
    tgt_in = single(ins, "TgtIn")
    tok_emb = single(ins, "TokEmb")
    pos_emb = maybe(ins, "PosEmb")
    ln_s, ln_b = single(ins, "FinalLnS"), single(ins, "FinalLnB")
    head_w = single(ins, "HeadW")
    params = {key: single(ins, slot) for slot, key in _STACK_SLOTS.items()}
    xparams = _unpack_cross(ins)
    num_heads = attrs["num_heads"]
    num_kv_heads = attrs.get("num_kv_heads") or num_heads
    b, Tt = tgt_in.shape
    d = params["ln1_s"].shape[1]
    memory = _encode_memory(ins, attrs, src, src_len)
    xk, xv = _project_cross_kv(memory, single(ins, "XKvW"),
                               num_kv_heads)  # [L, b, Hkv, Ts, dh]
    x = tok_emb[tgt_in]
    if pos_emb is not None:
        x = x + pos_emb[None, :Tt]

    def layer(h, inp):
        (layer_p, xk_l, xv_l, xlns, xlnb, xqw, xoutw) = inp
        xw = {"xlns": xlns, "xlnb": xlnb, "xqw": xqw, "xoutw": xoutw}
        q, k, v = _attn_proj(layer_p, h, num_heads, num_kv_heads)
        kx, vx = _expand_kv(k, v, num_heads)
        ctx = flash_attention(q, kx, vx, causal=True)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(b, Tt, d)
        h = h + jnp.einsum("btd,de->bte", ctx, layer_p["out_w"])
        h = _cross_attend(h, xw, xk_l, xv_l, src_len, num_heads)
        h2 = _ln(h, layer_p["ln2_s"], layer_p["ln2_b"])
        ff = jax.nn.gelu(jnp.einsum("btd,df->btf", h2, layer_p["ff_w1"])
                         + layer_p["ff_b1"])
        h = h + jnp.einsum("btf,fd->btd", ff, layer_p["ff_w2"]) \
            + layer_p["ff_b2"]
        return h, None

    h, _ = jax.lax.scan(
        layer, x,
        (params, xk, xv, xparams["xlns"], xparams["xlnb"],
         xparams["xqw"], xparams["xoutw"]))
    hn = _ln(h, ln_s, ln_b)
    logits = jnp.einsum("btd,dv->btv", hn, head_w).astype(jnp.float32)
    return out(Logits=logits)

"""Activation ops.

Covers the reference's activation zoo
(/root/reference/paddle/operators/activation_op.cc — ~20 registrations, and
the legacy set in gserver/activations/ActivationFunction.cpp — 17 types).
All are single jnp/jax.nn calls; XLA fuses them into adjacent matmuls/convs
so there is no standalone kernel cost on TPU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op
from .common import out, single


def _unary(op):
    def fn(attrs, ins):
        return out(Out=op(single(ins, "X")))

    return fn


register_op("relu", _unary(jax.nn.relu))
register_op("sigmoid", _unary(jax.nn.sigmoid))
register_op("logsigmoid", _unary(jax.nn.log_sigmoid))
register_op("tanh", _unary(jnp.tanh))
register_op("exp", _unary(jnp.exp))
register_op("log", _unary(jnp.log))
register_op("sqrt", _unary(jnp.sqrt))
register_op("rsqrt", _unary(jax.lax.rsqrt))
register_op("abs", _unary(jnp.abs))
register_op("ceil", _unary(jnp.ceil))
register_op("floor", _unary(jnp.floor))
register_op("round", _unary(jnp.round))
register_op("reciprocal", _unary(jnp.reciprocal))
register_op("square", _unary(jnp.square))
register_op("softplus", _unary(jax.nn.softplus))
register_op("softsign", _unary(jax.nn.soft_sign))
register_op("gelu", _unary(jax.nn.gelu))
register_op("sin", _unary(jnp.sin))
register_op("cos", _unary(jnp.cos))


@register_op("tanh_shrink")
def tanh_shrink(attrs, ins):
    x = single(ins, "X")
    return out(Out=x - jnp.tanh(x))


@register_op("softshrink")
def softshrink(attrs, ins):
    x = single(ins, "X")
    lam = attrs.get("lambda", 0.5)
    return out(Out=jnp.where(x > lam, x - lam, jnp.where(x < -lam, x + lam, 0.0)))


@register_op("hard_shrink")
def hard_shrink(attrs, ins):
    x = single(ins, "X")
    t = attrs.get("threshold", 0.5)
    return out(Out=jnp.where(jnp.abs(x) > t, x, 0.0))


@register_op("brelu")
def brelu(attrs, ins):
    x = single(ins, "X")
    return out(Out=jnp.clip(x, attrs.get("t_min", 0.0), attrs.get("t_max", 24.0)))


@register_op("relu6")
def relu6(attrs, ins):
    return out(Out=jnp.clip(single(ins, "X"), 0.0, attrs.get("threshold", 6.0)))


@register_op("leaky_relu")
def leaky_relu(attrs, ins):
    x = single(ins, "X")
    return out(Out=jax.nn.leaky_relu(x, negative_slope=attrs.get("alpha", 0.02)))


@register_op("elu")
def elu(attrs, ins):
    return out(Out=jax.nn.elu(single(ins, "X"), alpha=attrs.get("alpha", 1.0)))


@register_op("pow")
def pow_op(attrs, ins):
    x = single(ins, "X")
    return out(Out=jnp.power(x, jnp.asarray(attrs.get("factor", 1.0), dtype=x.dtype)))


@register_op("stanh")
def stanh(attrs, ins):
    x = single(ins, "X")
    a = attrs.get("scale_a", 2.0 / 3.0)
    b = attrs.get("scale_b", 1.7159)
    return out(Out=b * jnp.tanh(a * x))


@register_op("hard_sigmoid")
def hard_sigmoid(attrs, ins):
    x = single(ins, "X")
    slope = attrs.get("slope", 0.2)
    offset = attrs.get("offset", 0.5)
    return out(Out=jnp.clip(slope * x + offset, 0.0, 1.0))


@register_op("thresholded_relu")
def thresholded_relu(attrs, ins):
    x = single(ins, "X")
    t = attrs.get("threshold", 1.0)
    return out(Out=jnp.where(x > t, x, 0.0))


@register_op("swish")
def swish(attrs, ins):
    x = single(ins, "X")
    beta = attrs.get("beta", 1.0)
    return out(Out=x * jax.nn.sigmoid(beta * x))


@register_op("softmax")
def softmax(attrs, ins):
    return out(Out=jax.nn.softmax(single(ins, "X"), axis=attrs.get("axis", -1)))


@register_op("log_softmax")
def log_softmax(attrs, ins):
    return out(Out=jax.nn.log_softmax(single(ins, "X"), axis=attrs.get("axis", -1)))


@register_op("maxout")
def maxout(attrs, ins):
    # NCHW image form (reference maxout_op.cc) and the v1 2-D feature form
    # (reference MaxOutLayer on flattened vectors): channels split into
    # `groups` consecutive chunks, elementwise max across the chunk.
    x = single(ins, "X")
    groups = attrs["groups"]
    if x.ndim == 2:
        n, d = x.shape
        return out(Out=jnp.max(x.reshape(n, d // groups, groups), axis=2))
    n, c, h, w = x.shape
    return out(Out=jnp.max(x.reshape(n, c // groups, groups, h, w), axis=2))


@register_op("prelu")
def prelu(attrs, ins):
    x = single(ins, "X")
    alpha = single(ins, "Alpha")
    return out(Out=jnp.where(x > 0, x, alpha * x))

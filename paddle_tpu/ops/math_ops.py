"""Matrix-product ops — the MXU path.

Replaces the reference's mul/matmul kernels that bottom out in cuBLAS gemm
(/root/reference/paddle/operators/mul_op.cc, matmul_op.cc,
 operators/math/math_function.cc). On TPU these are single jnp.dot/einsum
calls that XLA tiles onto the 128x128 systolic array; mixed bf16/f32
accumulation is controlled with ``precision`` rather than hand-written
kernels.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import register_op
from .common import out, single


def _flatten2d(x, num_col_dims):
    lead = int(np.prod(x.shape[:num_col_dims])) if num_col_dims > 0 else 1
    return x.reshape(lead, -1)


from .common import amp_cast
from .common import mxu_precision as _precision


@register_op("mul")
def mul(attrs, ins):
    """Reference mul_op: flatten X to 2-D at x_num_col_dims, ditto Y, matmul."""
    x = single(ins, "X")
    y = single(ins, "Y")
    xd = attrs.get("x_num_col_dims", 1)
    yd = attrs.get("y_num_col_dims", 1)
    x2 = _flatten2d(x, xd)
    y2 = y.reshape(int(np.prod(y.shape[:yd])), -1)
    x2, y2 = amp_cast(x2, y2)
    # Plain XLA dot. A fused Pallas dX+dW backward was tried (round 3) and
    # measured SLOWER than XLA's two gradient dots under the 16 MB
    # scoped-vmem limit for custom calls — see PERF.md "fused linear
    # backward: tombstone".
    res = jax.lax.dot_general(x2, y2, (((1,), (0,)), ((), ())),
                              precision=_precision(x2, y2))
    out_shape = x.shape[:xd] + y.shape[yd:]
    return out(Out=res.reshape(out_shape))


@register_op("matmul")
def matmul(attrs, ins):
    x = single(ins, "X")
    y = single(ins, "Y")
    if attrs.get("transpose_X", False):
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if attrs.get("transpose_Y", False):
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    x, y = amp_cast(x, y)
    res = jnp.matmul(x, y, precision=_precision(x, y))
    alpha = attrs.get("alpha", 1.0)
    if alpha != 1.0:
        res = res * jnp.asarray(alpha, dtype=res.dtype)
    return out(Out=res)


@register_op("dot")
def dot(attrs, ins):
    x = single(ins, "X")
    y = single(ins, "Y")
    return out(Out=jnp.sum(x * y, axis=-1, keepdims=True))


@register_op("cos_sim")
def cos_sim(attrs, ins):
    x = single(ins, "X")
    y = single(ins, "Y")
    xn = jnp.sqrt(jnp.sum(x * x, axis=-1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(y * y, axis=-1, keepdims=True))
    sim = jnp.sum(x * y, axis=-1, keepdims=True) / (xn * yn + 1e-12)
    return {"Out": [sim], "XNorm": [xn], "YNorm": [yn]}

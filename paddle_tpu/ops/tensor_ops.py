"""Tensor creation / manipulation / elementwise / reduction ops.

TPU-native kernels for the reference op families in
/root/reference/paddle/operators (fill_constant_op.cc, gaussian_random_op.cc,
uniform_random_op.cc, elementwise_*_op.cc, reduce_op.cc, concat_op.cc,
split_op.cc, reshape_op.cc, transpose_op.cc, cast_op.cc, sum_op.cc,
scale_op.cc, clip_op.cc, top_k_op.cc, lookup_table_op.cc, accuracy_op.cc,
fill_constant_batch_size_like_op.cc, increment_op.cc, assign ops).
Each is a pure JAX function; gradients come from jax.vjp in the generic
backward pass unless a custom grad is registered.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import register_op
from ..core.selected_rows import SelectedRows
from ..core.types import to_dtype
from .common import broadcast_to_x, maybe, out, single


# --- creation ---------------------------------------------------------------
@register_op("fill_constant")
def fill_constant(attrs, ins):
    dtype = to_dtype(attrs.get("dtype", "float32"))
    shape = tuple(attrs.get("shape", ()))
    return out(Out=jnp.full(shape, attrs.get("value", 0.0), dtype=dtype))


def _batch_size_like_shape(attrs, ref):
    """Declared shape with the output batch dim copied from ``ref``'s
    (the *_batch_size_like op family contract)."""
    shape = list(attrs["shape"])
    shape[attrs.get("output_dim_idx", 0)] = ref.shape[
        attrs.get("input_dim_idx", 0)]
    return tuple(shape)


@register_op("fill_constant_batch_size_like")
def fill_constant_batch_size_like(attrs, ins):
    ref = single(ins, "Input")
    dtype = to_dtype(attrs.get("dtype", "float32"))
    return out(Out=jnp.full(_batch_size_like_shape(attrs, ref),
                            attrs.get("value", 0.0), dtype=dtype))


@register_op("gaussian_random", needs_rng=True)
def gaussian_random(attrs, ins, rng):
    dtype = to_dtype(attrs.get("dtype", "float32"))
    shape = tuple(attrs["shape"])
    mean = attrs.get("mean", 0.0)
    std = attrs.get("std", 1.0)
    return out(Out=mean + std * jax.random.normal(rng, shape, dtype=dtype))


@register_op("gaussian_random_batch_size_like", needs_rng=True)
def gaussian_random_batch_size_like(attrs, ins, rng):
    """Gaussian noise whose batch dim copies Input's
    (gaussian_random_batch_size_like_op.cc) — the reparameterization-trick
    noise source: an rng LEAF, so grads flow only through mu/sigma."""
    ref = single(ins, "Input")
    dtype = to_dtype(attrs.get("dtype", "float32"))
    noise = jax.random.normal(rng, _batch_size_like_shape(attrs, ref),
                              dtype=dtype)
    return out(Out=attrs.get("mean", 0.0) + attrs.get("std", 1.0) * noise)


@register_op("uniform_random", needs_rng=True)
def uniform_random(attrs, ins, rng):
    dtype = to_dtype(attrs.get("dtype", "float32"))
    shape = tuple(attrs["shape"])
    lo = attrs.get("min", -1.0)
    hi = attrs.get("max", 1.0)
    return out(Out=jax.random.uniform(rng, shape, dtype=dtype, minval=lo, maxval=hi))


@register_op("truncated_gaussian_random", needs_rng=True)
def truncated_gaussian_random(attrs, ins, rng):
    dtype = to_dtype(attrs.get("dtype", "float32"))
    shape = tuple(attrs["shape"])
    mean = attrs.get("mean", 0.0)
    std = attrs.get("std", 1.0)
    x = jax.random.truncated_normal(rng, -2.0, 2.0, shape, dtype=dtype)
    return out(Out=mean + std * x)


@register_op("assign")
def assign(attrs, ins):
    return out(Out=single(ins, "X"))


@register_op("assign_value")
def assign_value(attrs, ins):
    dtype = to_dtype(attrs.get("dtype", "float32"))
    vals = np.asarray(attrs["values"], dtype=dtype).reshape(tuple(attrs["shape"]))
    return out(Out=jnp.asarray(vals))


@register_op("cast")
def cast(attrs, ins):
    dtype = to_dtype(attrs.get("out_dtype", attrs.get("dtype", "float32")))
    return out(Out=single(ins, "X").astype(dtype))


@register_op("increment")
def increment(attrs, ins):
    x = single(ins, "X")
    return out(Out=x + jnp.asarray(attrs.get("step", 1.0), dtype=x.dtype))


# --- shape manipulation -----------------------------------------------------
@register_op("reshape")
def reshape(attrs, ins):
    x = single(ins, "X")
    shape = list(attrs["shape"])
    # reference semantics (reshape_op.cc): 0 means copy the input dim.
    shape = [x.shape[i] if d == 0 else d for i, d in enumerate(shape)]
    return out(Out=x.reshape(tuple(shape)))


@register_op("transpose")
def transpose(attrs, ins):
    return out(Out=jnp.transpose(single(ins, "X"), axes=tuple(attrs["axis"])))


@register_op("concat")
def concat(attrs, ins):
    return out(Out=jnp.concatenate(ins["X"], axis=attrs.get("axis", 0)))


@register_op("split")
def split(attrs, ins):
    x = single(ins, "X")
    axis = attrs.get("axis", 0)
    if attrs.get("sections"):
        idx = np.cumsum(attrs["sections"])[:-1]
        parts = jnp.split(x, idx, axis=axis)
    else:
        parts = jnp.split(x, attrs["num"], axis=axis)
    return {"Out": list(parts)}


@register_op("slice")
def slice_op(attrs, ins):
    x = single(ins, "X")
    axes = attrs["axes"]
    starts = attrs["starts"]
    ends = attrs["ends"]
    idx = [slice(None)] * x.ndim
    for ax, st, en in zip(axes, starts, ends):
        idx[ax] = slice(st, en)
    return out(Out=x[tuple(idx)])


@register_op("squeeze")
def squeeze(attrs, ins):
    x = single(ins, "X")
    axes = attrs.get("axes") or [i for i, d in enumerate(x.shape) if d == 1]
    return out(Out=jnp.squeeze(x, axis=tuple(axes)))


@register_op("unsqueeze")
def unsqueeze(attrs, ins):
    return out(Out=jnp.expand_dims(single(ins, "X"), axis=tuple(attrs["axes"])))


@register_op("expand")
def expand(attrs, ins):
    x = single(ins, "X")
    times = attrs["expand_times"]
    return out(Out=jnp.tile(x, tuple(times)))


@register_op("stack")
def stack(attrs, ins):
    return out(Y=jnp.stack(ins["X"], axis=attrs.get("axis", 0)))


@register_op("pad")
def pad(attrs, ins):
    x = single(ins, "X")
    p = attrs["paddings"]  # flat [before0, after0, before1, after1, ...]
    widths = [(p[2 * i], p[2 * i + 1]) for i in range(x.ndim)]
    return out(Out=jnp.pad(x, widths, constant_values=attrs.get("pad_value", 0.0)))


@register_op("crop")
def crop(attrs, ins):
    x = single(ins, "X")
    offsets = attrs["offsets"]
    shape = attrs["shape"]
    # -1 in the target shape = keep the input's full extent on that axis
    # (the dynamic-batch dim in particular)
    idx = tuple(slice(o, None if s == -1 else o + s)
                for o, s in zip(offsets, shape))
    return out(Out=x[idx])


# --- elementwise binary (broadcast semantics per elementwise_op.h) ----------
def _elementwise(op):
    def fn(attrs, ins):
        from ..core.selected_rows import densify

        # a SelectedRows operand (sparse grad flowing into a dense
        # elementwise consumer, e.g. the gradient-accumulation
        # ``acc += grad``) takes its dense view — the row-granular
        # fast path belongs to the sparse_* optimizer ops only
        x = densify(single(ins, "X"))
        y = broadcast_to_x(x, densify(single(ins, "Y")),
                           attrs.get("axis", -1))
        return out(Out=op(x, y))

    return fn


register_op("elementwise_add", _elementwise(jnp.add))
register_op("elementwise_sub", _elementwise(jnp.subtract))
register_op("elementwise_mul", _elementwise(jnp.multiply))
register_op("elementwise_div", _elementwise(jnp.divide))
register_op("elementwise_max", _elementwise(jnp.maximum))
register_op("elementwise_min", _elementwise(jnp.minimum))
register_op("elementwise_pow", _elementwise(jnp.power))


@register_op("sum")
def sum_op(attrs, ins):
    xs = ins["X"]
    # SelectedRows-aware accumulation (grad fan-out of a sparse embedding):
    # sparse+sparse stays sparse (row concat); any dense operand densifies.
    sparse = [x for x in xs if isinstance(x, SelectedRows)]
    dense = [x for x in xs if not isinstance(x, SelectedRows)]
    acc = None
    if sparse:
        acc = sparse[0]
        for x in sparse[1:]:
            acc = acc + x
        if dense:
            acc = acc.to_dense()
    for x in dense:
        acc = x if acc is None else acc + x
    return out(Out=acc)


@register_op("scale")
def scale(attrs, ins):
    x = single(ins, "X")
    if isinstance(x, SelectedRows):
        if attrs.get("bias", 0.0):
            raise ValueError("scale with bias is not defined on SelectedRows")
        return out(Out=x.scale(jnp.asarray(attrs.get("scale", 1.0),
                                           dtype=x.dtype)))
    s = jnp.asarray(attrs.get("scale", 1.0), dtype=x.dtype)
    b = jnp.asarray(attrs.get("bias", 0.0), dtype=x.dtype)
    if attrs.get("bias_after_scale", True):
        return out(Out=x * s + b)
    return out(Out=(x + b) * s)


@register_op("clip")
def clip(attrs, ins):
    x = single(ins, "X")
    if isinstance(x, SelectedRows):
        # merge duplicate rows FIRST: the bound applies to the effective
        # (dense-equivalent) per-row gradient, not each occurrence
        m = x.merged()
        return out(Out=SelectedRows(
            m.rows, jnp.clip(m.values, attrs["min"], attrs["max"]), m.height))
    return out(Out=jnp.clip(x, attrs["min"], attrs["max"]))


def _sq_l2(g):
    """Squared L2 norm of a gradient; SelectedRows are deduplicated first so
    repeated rows contribute their summed (dense-equivalent) value."""
    if isinstance(g, SelectedRows):
        return jnp.sum(jnp.square(g.merged().values.astype(jnp.float32)))
    return jnp.sum(jnp.square(g.astype(jnp.float32)))


def _rescale(g, factor):
    if isinstance(g, SelectedRows):
        return SelectedRows(g.rows,
                            g.values * factor.astype(g.values.dtype),
                            g.height)
    return g * factor.astype(g.dtype)


@register_op("clip_by_norm")
def clip_by_norm(attrs, ins):
    """Rescale X so its L2 norm is at most max_norm (clip_by_norm_op)."""
    x = single(ins, "X")
    max_norm = attrs["max_norm"]
    norm = jnp.sqrt(jnp.maximum(_sq_l2(x), 1e-12))
    factor = jnp.minimum(1.0, max_norm / norm)
    return out(Out=_rescale(x, factor))


@register_op("clip_by_global_norm")
def clip_by_global_norm(attrs, ins):
    """Jointly rescale every gradient in X so the global L2 norm of the set
    is at most max_norm — one fused kernel over all grads (the TPU-native
    form of the legacy trainer's gradient_clipping_threshold, applied
    per-parameter-update in ParameterConfig.proto)."""
    xs = ins["X"]
    max_norm = attrs["max_norm"]
    gnorm = jnp.sqrt(jnp.maximum(
        sum(_sq_l2(g) for g in xs), 1e-12))
    factor = jnp.minimum(1.0, max_norm / gnorm)
    return {"Out": [_rescale(g, factor) for g in xs]}


@register_op("l1_decay_sign")
def l1_decay_sign(attrs, ins):
    x = single(ins, "X")
    return out(Out=jnp.sign(x) * jnp.asarray(attrs["coeff"], dtype=x.dtype))


# --- reductions -------------------------------------------------------------
@register_op("mean")
def mean(attrs, ins):
    return out(Out=jnp.mean(single(ins, "X")))


def _reduce(op):
    def fn(attrs, ins):
        x = single(ins, "X")
        dim = attrs.get("dim")
        keep = attrs.get("keep_dim", False)
        if attrs.get("reduce_all", dim is None):
            return out(Out=op(x, keepdims=keep))
        axes = tuple(dim) if isinstance(dim, (list, tuple)) else (dim,)
        return out(Out=op(x, axis=axes, keepdims=keep))

    return fn


register_op("reduce_sum", _reduce(jnp.sum))
register_op("reduce_mean", _reduce(jnp.mean))
register_op("reduce_max", _reduce(jnp.max))
register_op("reduce_min", _reduce(jnp.min))
register_op("reduce_prod", _reduce(jnp.prod))


@register_op("argmax")
def argmax(attrs, ins):
    x = single(ins, "X")
    return out(Out=jnp.argmax(x, axis=attrs.get("axis", -1)).astype(jnp.int64))


# --- comparison / logical ---------------------------------------------------
def _compare(op):
    def fn(attrs, ins):
        x = single(ins, "X")
        y = broadcast_to_x(x, single(ins, "Y"), attrs.get("axis", -1))
        return out(Out=op(x, y))

    return fn


register_op("equal", _compare(jnp.equal))
register_op("not_equal", _compare(jnp.not_equal))
register_op("less_than", _compare(jnp.less))
register_op("less_equal", _compare(jnp.less_equal))
register_op("greater_than", _compare(jnp.greater))
register_op("greater_equal", _compare(jnp.greater_equal))
register_op("logical_and", _compare(jnp.logical_and))
register_op("logical_or", _compare(jnp.logical_or))
register_op("logical_xor", _compare(jnp.logical_xor))


@register_op("logical_not")
def logical_not(attrs, ins):
    return out(Out=jnp.logical_not(single(ins, "X")))


# --- indexing ---------------------------------------------------------------
def _lookup_table_grad(attrs, ins, outs, ogs):
    """Embedding gradient, sparse or dense.

    With ``is_sparse`` the gradient is a SelectedRows — (ids, row grads)
    with NO [V, D] buffer — exactly the reference's design
    (lookup_table_op.cc:59 emits SelectedRows; selected_rows.h), consumed
    row-granularly by the optimizer ops. Without it, the dense equivalent
    via scatter-add (fine for small vocabularies).
    """
    w = single(ins, "W")
    ids = single(ins, "Ids").reshape(-1)
    og = ogs["Out"][0].reshape(ids.shape[0], w.shape[-1])
    pad = attrs.get("padding_idx")
    if pad is not None and pad >= 0:
        # the forward zeroes the padding row's output, so its grad is 0:
        # point padding lookups at the out-of-range sentinel so scatters
        # drop them (both paths)
        ids = jnp.where(ids == pad, w.shape[0], ids)
    if attrs.get("is_sparse", False):
        return {"W": [SelectedRows(ids, og.astype(w.dtype), w.shape[0])],
                "Ids": [None]}
    dw = jnp.zeros_like(w).at[ids].add(og.astype(w.dtype), mode="drop")
    return {"W": [dw], "Ids": [None]}


def _vocab_sharded_gather(attrs, w, flat):
    """The shard_map gather when the executor mesh carries the plan's
    vocab axis and the table divides (the vocab_sharded_plan path —
    each device owns a [V/n, D] row block and one psum exchanges the
    looked-up rows); None selects the serial gather — the SAME program
    runs on one device (and under abstract shape inference, where no
    mesh is published)."""
    if not attrs.get("is_sparse", False):
        return None
    from ..parallel.context import current_mesh
    from ..parallel.sharded_embedding import rows_per_shard, vp_lookup

    mesh = current_mesh()
    axis = attrs.get("vocab_axis", "mp")
    if mesh is None or not rows_per_shard(w.shape[0], mesh, axis):
        return None
    return vp_lookup(w, flat, mesh, vocab_axis=axis,
                     data_axis=attrs.get("data_axis", "dp"))


@register_op("lookup_table", grad_fn=_lookup_table_grad)
def lookup_table(attrs, ins):
    w = single(ins, "W")
    ids = single(ins, "Ids")
    squeeze_last = ids.ndim > 1 and ids.shape[-1] == 1
    flat = ids.reshape(-1)
    rows = _vocab_sharded_gather(attrs, w, flat)
    if rows is None:
        rows = w[flat]
    if attrs.get("padding_idx") is not None and attrs.get("padding_idx", -1) >= 0:
        pad_idx = attrs["padding_idx"]
        emb = jnp.where((flat == pad_idx)[:, None], 0.0, rows)
    else:
        emb = rows
    shape = (ids.shape[:-1] if squeeze_last else ids.shape) + (w.shape[-1],)
    return out(Out=emb.reshape(shape))


@register_op("gather")
def gather(attrs, ins):
    x = single(ins, "X")
    idx = single(ins, "Index").reshape(-1)
    return out(Out=jnp.take(x, idx, axis=0))


@register_op("top_k")
def top_k(attrs, ins):
    x = single(ins, "X")
    k = attrs["k"]
    vals, idx = jax.lax.top_k(x, k)
    return {"Out": [vals], "Indices": [idx.astype(jnp.int64)]}


@register_op("one_hot")
def one_hot(attrs, ins):
    x = single(ins, "X")
    depth = attrs["depth"]
    flat = x.reshape(x.shape[:-1] if (x.ndim > 1 and x.shape[-1] == 1) else x.shape)
    return out(Out=jax.nn.one_hot(flat, depth, dtype=jnp.float32))


# --- metrics ----------------------------------------------------------------
@register_op("accuracy")
def accuracy(attrs, ins):
    """Inputs: Out (top-k values), Indices (top-k indices), Label [N,1]."""
    idx = single(ins, "Indices")
    label = single(ins, "Label").reshape(-1, 1)
    correct = jnp.sum(jnp.any(idx == label, axis=1))
    total = idx.shape[0]
    acc = correct.astype(jnp.float32) / total
    return {
        "Accuracy": [acc],
        "Correct": [correct.astype(jnp.int32)],
        "Total": [jnp.asarray(total, dtype=jnp.int32)],
    }


# --- IO markers (handled by Executor.run feed/fetch contract) ---------------
@register_op("feed")
def feed(attrs, ins):
    return out(Out=single(ins, "X")) if "X" in ins else None


@register_op("fetch")
def fetch(attrs, ins):
    return out(Out=single(ins, "X"))

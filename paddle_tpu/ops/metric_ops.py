"""Metric ops: confusion counts, AUC histograms, edit distance.

TPU-native equivalents of the reference's evaluator kernels
(/root/reference/paddle/gserver/evaluators/Evaluator.cpp:
PrecisionRecallEvaluator, AucEvaluator, CTCErrorEvaluator;
/root/reference/paddle/operators/edit_distance_op.{cc,h}, auc_op.cc).
All are batched, loop-free formulations: bincounts via segment_sum and the
Levenshtein DP as a lax.scan over anti-diagonal-free row updates, vmapped
over the batch — no per-sequence host loops.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op
from .common import maybe, out, single


@register_op("confusion_counts")
def confusion_counts(attrs, ins):
    """Per-class TP/FP/FN from predictions (argmax of Pred if 2-D scores,
    else raw int preds) vs int labels."""
    pred = single(ins, "Pred")
    label = single(ins, "Label").reshape(-1).astype(jnp.int32)
    n = int(attrs["num_classes"])
    if pred.ndim == 2 and pred.shape[-1] > 1:
        pred = jnp.argmax(pred, axis=-1)
    elif jnp.issubdtype(pred.dtype, jnp.floating):
        # single-column probability scores: threshold, don't truncate
        pred = (pred.reshape(-1) > 0.5)
    pred = pred.reshape(-1).astype(jnp.int32)
    hit = pred == label
    tp = jax.ops.segment_sum(hit.astype(jnp.int32), label, num_segments=n)
    pred_cnt = jax.ops.segment_sum(jnp.ones_like(label, jnp.int32), pred,
                                   num_segments=n)
    label_cnt = jax.ops.segment_sum(jnp.ones_like(label, jnp.int32), label,
                                    num_segments=n)
    return {"TP": [tp], "FP": [pred_cnt - tp], "FN": [label_cnt - tp]}


@register_op("auc_histogram")
def auc_histogram(attrs, ins):
    """Histogram positive-class scores into num_thresholds buckets, split by
    binary label (the streaming-AUC state update, auc_op.cc)."""
    score = single(ins, "Score")
    label = single(ins, "Label").reshape(-1)
    k = int(attrs.get("num_thresholds", 200))
    if score.ndim == 2:
        # scores over 2 classes -> P(class 1); single column -> itself
        score = score[:, -1]
    score = score.reshape(-1)
    bucket = jnp.clip((score * k).astype(jnp.int32), 0, k - 1)
    is_pos = label.astype(jnp.int32) > 0
    ones = jnp.ones_like(bucket, jnp.int32)
    pos = jax.ops.segment_sum(jnp.where(is_pos, ones, 0), bucket,
                              num_segments=k)
    neg = jax.ops.segment_sum(jnp.where(is_pos, 0, ones), bucket,
                              num_segments=k)
    return {"Pos": [pos], "Neg": [neg]}


def _length_mask(lengths, b, L):
    if lengths is None:
        return jnp.ones((b, L), jnp.float32)
    lengths = lengths.reshape(-1).astype(jnp.int32)
    return (jnp.arange(L, dtype=jnp.int32)[None, :]
            < lengths[:, None]).astype(jnp.float32)


@register_op("rank_auc", optional_inputs=("Pv", "Length"))
def rank_auc(attrs, ins):
    """Per-query click-through AUC (RankAucEvaluator,
    /root/reference/paddle/gserver/evaluators/Evaluator.cpp:514-592).

    Queries are dense padded rows: Score/Click/Pv are [b, L] with optional
    Length [b]. Each position i carries click_i positive events and
    (pv_i - click_i) negative events at score s_i; the reference's
    sort-and-trapezoid per query is equivalent to the pairwise form

        auc = sum_ij pos_i * neg_j * (1[s_i > s_j] + .5 * 1[s_i == s_j])
              / (sum pos * sum neg)

    (same-score pairs count half — the trapezoid's tie handling), which
    vectorizes as one [b, L, L] comparison instead of a host sort. Queries
    with no positive or no negative events score 0, as in the reference.
    Outputs AucSum (sum of per-query aucs) and QueryCount for streaming
    averaging.
    """
    score = single(ins, "Score")
    click = single(ins, "Click")
    if score.ndim == 3:
        score = score[..., -1]
    if score.ndim == 1:
        score, click = score[None, :], click[None, :]
    click = click.reshape(score.shape).astype(jnp.float32)
    pv = maybe(ins, "Pv")
    pv = (jnp.ones_like(click) if pv is None
          else pv.reshape(score.shape).astype(jnp.float32))
    b, L = score.shape
    m = _length_mask(maybe(ins, "Length"), b, L)
    pos = click * m
    neg = (pv - click) * m
    s = score.astype(jnp.float32)
    gt = (s[:, :, None] > s[:, None, :]).astype(jnp.float32)
    eq = (s[:, :, None] == s[:, None, :]).astype(jnp.float32)
    conc = gt + 0.5 * eq  # [b, L, L]
    num = jnp.einsum("bi,bij,bj->b", pos, conc, neg)
    denom = pos.sum(-1) * neg.sum(-1)
    auc = jnp.where(denom > 0, num / jnp.maximum(denom, 1e-30), 0.0)
    return out(AucSum=auc.sum(), QueryCount=jnp.asarray(b, jnp.float32))


@register_op("pnpair_counts", optional_inputs=("Weight", "Length"))
def pnpair_counts(attrs, ins):
    """Positive/negative/special pair counts within each query
    (PnpairEvaluator, /root/reference/paddle/gserver/evaluators/
    Evaluator.cpp:873-1000).

    Score/Label/[Weight] are dense padded [b, L] per-query rows (the
    reference instead buffers the whole pass on host and groups by a
    query-id column; the padded layout keeps the count update in-graph).
    For each unordered in-query pair with label_i != label_j:
    concordant (score and label order agree) -> Pos, discordant -> Neg,
    score tie -> Spe; pair weight is the mean of the two sample weights.
    """
    score = single(ins, "Score")
    label = single(ins, "Label")
    if score.ndim == 3:
        score = score[..., -1]
    if score.ndim == 1:
        score, label = score[None, :], label[None, :]
    label = label.reshape(score.shape).astype(jnp.float32)
    w = maybe(ins, "Weight")
    w = (jnp.ones_like(label) if w is None
         else w.reshape(score.shape).astype(jnp.float32))
    b, L = score.shape
    m = _length_mask(maybe(ins, "Length"), b, L)
    s = score.astype(jnp.float32)
    pair_m = m[:, :, None] * m[:, None, :]
    # unordered pairs: strict upper triangle
    iu = jnp.triu(jnp.ones((L, L), jnp.float32), k=1)[None]
    valid = pair_m * iu * (label[:, :, None] != label[:, None, :])
    pw = 0.5 * (w[:, :, None] + w[:, None, :])
    s_gt = s[:, :, None] > s[:, None, :]
    s_lt = s[:, :, None] < s[:, None, :]
    l_gt = label[:, :, None] > label[:, None, :]
    l_lt = label[:, :, None] < label[:, None, :]
    conc = (s_gt & l_gt) | (s_lt & l_lt)
    disc = (s_gt & l_lt) | (s_lt & l_gt)
    tie = ~(s_gt | s_lt)
    pos = (valid * pw * conc).sum()
    negc = (valid * pw * disc).sum()
    spe = (valid * pw * tie).sum()
    return out(Pos=pos, Neg=negc, Spe=spe)


@register_op("detection_map_counts",
             optional_inputs=("DetLength", "GtLength"))
def detection_map_counts(attrs, ins):
    """Streaming detection-mAP state update (DetectionMAPEvaluator,
    /root/reference/paddle/gserver/evaluators/DetectionMAPEvaluator.cpp).

    Inputs per image row: DetBoxes [b, M, 4] (x1,y1,x2,y2), DetScores
    [b, M], DetClasses [b, M] int, GtBoxes [b, G, 4], GtClasses [b, G] int,
    with valid counts DetLength/GtLength [b]. Greedy high-score-first
    matching (lax.scan over the M sorted detections, carry = matched-gt
    mask) marks each detection TP (IoU >= overlap_threshold with an
    unmatched same-class gt) or FP. Instead of the reference's host-side
    score-sorted map of per-class TP/FP lists, counts are bucketed by score
    into num_buckets bins per class — the same histogram-state trick as
    auc_histogram — so the evaluator state is a fixed [C, K] tensor and the
    PR curve/AP integral is recovered at eval() from the bin cumsums.
    Outputs TP [C, K], FP [C, K], GtCount [C].
    """
    dbox = single(ins, "DetBoxes").astype(jnp.float32)
    dscore = single(ins, "DetScores").astype(jnp.float32)
    dcls = single(ins, "DetClasses").reshape(dscore.shape).astype(jnp.int32)
    gbox = single(ins, "GtBoxes").astype(jnp.float32)
    gcls = single(ins, "GtClasses")
    b, M = dscore.shape
    G = gbox.shape[1]
    gcls = gcls.reshape((b, G)).astype(jnp.int32)
    C = int(attrs["num_classes"])
    K = int(attrs.get("num_buckets", 200))
    thresh = float(attrs.get("overlap_threshold", 0.5))
    dmask = _length_mask(maybe(ins, "DetLength"), b, M) > 0
    gmask = _length_mask(maybe(ins, "GtLength"), b, G) > 0

    def iou(a, bx):  # a [M, 4], bx [G, 4] -> [M, G]
        lt = jnp.maximum(a[:, None, :2], bx[None, :, :2])
        rb = jnp.minimum(a[:, None, 2:], bx[None, :, 2:])
        wh = jnp.clip(rb - lt, 0.0)
        inter = wh[..., 0] * wh[..., 1]
        area_a = ((a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1]))[:, None]
        area_b = ((bx[:, 2] - bx[:, 0]) * (bx[:, 3] - bx[:, 1]))[None, :]
        return inter / jnp.maximum(area_a + area_b - inter, 1e-10)

    def match_one(db, ds, dc, dm, gb, gc, gm):
        order = jnp.argsort(-jnp.where(dm, ds, -jnp.inf))
        overlaps = iou(db, gb)  # [M, G]
        same = (dc[:, None] == gc[None, :]) & gm[None, :]
        cand = jnp.where(same, overlaps, -1.0)  # [M, G]

        def step(matched, i):
            ious_i = jnp.where(matched, -1.0, cand[i])
            j = jnp.argmax(ious_i)
            hit = (ious_i[j] >= thresh) & dm[i]
            matched = matched.at[j].set(matched[j] | hit)
            return matched, hit

        _, tp_sorted = jax.lax.scan(step, jnp.zeros((G,), bool), order)
        # unsort back to input order
        tp = jnp.zeros((M,), bool).at[order].set(tp_sorted)
        return tp

    tp = jax.vmap(match_one)(dbox, dscore, dcls, dmask, gbox, gcls, gmask)
    fp = dmask & ~tp
    # bucket (class, score-bin) counts; invalid detections -> segment C*K
    bins = jnp.clip((dscore * K).astype(jnp.int32), 0, K - 1)
    seg = jnp.where(dmask, jnp.clip(dcls, 0, C - 1) * K + bins, C * K)
    tp_hist = jax.ops.segment_sum(
        tp.reshape(-1).astype(jnp.int32), seg.reshape(-1),
        num_segments=C * K + 1)[:-1].reshape(C, K)
    fp_hist = jax.ops.segment_sum(
        fp.reshape(-1).astype(jnp.int32), seg.reshape(-1),
        num_segments=C * K + 1)[:-1].reshape(C, K)
    gseg = jnp.where(gmask, jnp.clip(gcls, 0, C - 1), C)
    gt_cnt = jax.ops.segment_sum(
        jnp.ones((b * G,), jnp.int32), gseg.reshape(-1),
        num_segments=C + 1)[:-1]
    return out(TP=tp_hist, FP=fp_hist, GtCount=gt_cnt)


@register_op("edit_distance", optional_inputs=("HypsLength", "RefsLength"))
def edit_distance(attrs, ins):
    """Batched Levenshtein distance (edit_distance_op.h) between padded int
    sequences Hyps [b, Th] and Refs [b, Tr] with optional lengths.

    DP over ref positions as a lax.scan of row updates; each row update is
    itself a (associative-scan-free) sequential min over the hyp axis,
    expressed as a second lax.scan — O(Tr) XLA loop iterations with [b, Th]
    vector work each, instead of the reference's per-pair CPU DP.
    """
    hyp = single(ins, "Hyps")
    ref = single(ins, "Refs")
    if hyp.ndim == 3:
        hyp = hyp[..., 0]
    if ref.ndim == 3:
        ref = ref[..., 0]
    b, Th = hyp.shape
    Tr = ref.shape[1]
    hlen = maybe(ins, "HypsLength")
    rlen = maybe(ins, "RefsLength")
    if hlen is None:
        hlen = jnp.full((b,), Th, jnp.int32)
    if rlen is None:
        rlen = jnp.full((b,), Tr, jnp.int32)
    hlen = hlen.reshape(-1).astype(jnp.int32)
    rlen = rlen.reshape(-1).astype(jnp.int32)
    normalized = attrs.get("normalized", False)

    j_idx = jnp.arange(Th + 1, dtype=jnp.int32)  # [Th+1]
    # row[b, j] = edit distance between ref[:i] and hyp[:j]; row0[j] = j
    row0 = jnp.broadcast_to(j_idx[None, :], (b, Th + 1)).astype(jnp.int32)
    j1 = jnp.arange(1, Th + 1, dtype=jnp.int32)

    def outer(row, i):
        ref_i = jax.lax.dynamic_index_in_dim(ref, i, axis=1, keepdims=False)
        sub_cost = (hyp != ref_i[:, None]).astype(jnp.int32)  # [b, Th]
        diag = row[:, :-1] + sub_cost
        del_cost = row[:, 1:] + 1  # deletion from ref
        cand = jnp.minimum(diag, del_cost)  # [b, Th]
        # The sequential insert recurrence new[j] = min(cand[j-1], new[j-1]+1)
        # with new[0] = i+1 unrolls to new[j] = j + min(i+1, min_{k<=j}
        # (cand[k-1] - k)) — a parallel prefix-min instead of an O(Th) loop.
        cprime = cand - j1[None, :]
        prefix = jax.lax.associative_scan(jnp.minimum, cprime, axis=1)
        first = jnp.full((b, 1), i + 1, jnp.int32)
        tail = j1[None, :] + jnp.minimum(prefix, i + 1)
        new_row = jnp.concatenate([first, tail], axis=1)
        # rows beyond this batch item's ref length keep their last valid row
        active = (i < rlen)[:, None]
        new_row = jnp.where(active, new_row, row)
        return new_row, None

    final_row, _ = jax.lax.scan(outer, row0, jnp.arange(Tr, dtype=jnp.int32))
    dist = jnp.take_along_axis(final_row, hlen[:, None], axis=1)[:, 0]
    dist = dist.astype(jnp.float32)
    if normalized:
        dist = dist / jnp.maximum(rlen.astype(jnp.float32), 1.0)
    return out(Out=dist[:, None],
               SequenceNum=jnp.asarray(b, jnp.int32))

"""Metric ops: confusion counts, AUC histograms, edit distance.

TPU-native equivalents of the reference's evaluator kernels
(/root/reference/paddle/gserver/evaluators/Evaluator.cpp:
PrecisionRecallEvaluator, AucEvaluator, CTCErrorEvaluator;
/root/reference/paddle/operators/edit_distance_op.{cc,h}, auc_op.cc).
All are batched, loop-free formulations: bincounts via segment_sum and the
Levenshtein DP as a lax.scan over anti-diagonal-free row updates, vmapped
over the batch — no per-sequence host loops.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op
from .common import maybe, out, single


@register_op("confusion_counts")
def confusion_counts(attrs, ins):
    """Per-class TP/FP/FN from predictions (argmax of Pred if 2-D scores,
    else raw int preds) vs int labels."""
    pred = single(ins, "Pred")
    label = single(ins, "Label").reshape(-1).astype(jnp.int32)
    n = int(attrs["num_classes"])
    if pred.ndim == 2 and pred.shape[-1] > 1:
        pred = jnp.argmax(pred, axis=-1)
    elif jnp.issubdtype(pred.dtype, jnp.floating):
        # single-column probability scores: threshold, don't truncate
        pred = (pred.reshape(-1) > 0.5)
    pred = pred.reshape(-1).astype(jnp.int32)
    hit = pred == label
    tp = jax.ops.segment_sum(hit.astype(jnp.int32), label, num_segments=n)
    pred_cnt = jax.ops.segment_sum(jnp.ones_like(label, jnp.int32), pred,
                                   num_segments=n)
    label_cnt = jax.ops.segment_sum(jnp.ones_like(label, jnp.int32), label,
                                    num_segments=n)
    return {"TP": [tp], "FP": [pred_cnt - tp], "FN": [label_cnt - tp]}


@register_op("auc_histogram")
def auc_histogram(attrs, ins):
    """Histogram positive-class scores into num_thresholds buckets, split by
    binary label (the streaming-AUC state update, auc_op.cc)."""
    score = single(ins, "Score")
    label = single(ins, "Label").reshape(-1)
    k = int(attrs.get("num_thresholds", 200))
    if score.ndim == 2:
        # scores over 2 classes -> P(class 1); single column -> itself
        score = score[:, -1]
    score = score.reshape(-1)
    bucket = jnp.clip((score * k).astype(jnp.int32), 0, k - 1)
    is_pos = label.astype(jnp.int32) > 0
    ones = jnp.ones_like(bucket, jnp.int32)
    pos = jax.ops.segment_sum(jnp.where(is_pos, ones, 0), bucket,
                              num_segments=k)
    neg = jax.ops.segment_sum(jnp.where(is_pos, 0, ones), bucket,
                              num_segments=k)
    return {"Pos": [pos], "Neg": [neg]}


@register_op("edit_distance", optional_inputs=("HypsLength", "RefsLength"))
def edit_distance(attrs, ins):
    """Batched Levenshtein distance (edit_distance_op.h) between padded int
    sequences Hyps [b, Th] and Refs [b, Tr] with optional lengths.

    DP over ref positions as a lax.scan of row updates; each row update is
    itself a (associative-scan-free) sequential min over the hyp axis,
    expressed as a second lax.scan — O(Tr) XLA loop iterations with [b, Th]
    vector work each, instead of the reference's per-pair CPU DP.
    """
    hyp = single(ins, "Hyps")
    ref = single(ins, "Refs")
    if hyp.ndim == 3:
        hyp = hyp[..., 0]
    if ref.ndim == 3:
        ref = ref[..., 0]
    b, Th = hyp.shape
    Tr = ref.shape[1]
    hlen = maybe(ins, "HypsLength")
    rlen = maybe(ins, "RefsLength")
    if hlen is None:
        hlen = jnp.full((b,), Th, jnp.int32)
    if rlen is None:
        rlen = jnp.full((b,), Tr, jnp.int32)
    hlen = hlen.reshape(-1).astype(jnp.int32)
    rlen = rlen.reshape(-1).astype(jnp.int32)
    normalized = attrs.get("normalized", False)

    j_idx = jnp.arange(Th + 1, dtype=jnp.int32)  # [Th+1]
    # row[b, j] = edit distance between ref[:i] and hyp[:j]; row0[j] = j
    row0 = jnp.broadcast_to(j_idx[None, :], (b, Th + 1)).astype(jnp.int32)
    j1 = jnp.arange(1, Th + 1, dtype=jnp.int32)

    def outer(row, i):
        ref_i = jax.lax.dynamic_index_in_dim(ref, i, axis=1, keepdims=False)
        sub_cost = (hyp != ref_i[:, None]).astype(jnp.int32)  # [b, Th]
        diag = row[:, :-1] + sub_cost
        del_cost = row[:, 1:] + 1  # deletion from ref
        cand = jnp.minimum(diag, del_cost)  # [b, Th]
        # The sequential insert recurrence new[j] = min(cand[j-1], new[j-1]+1)
        # with new[0] = i+1 unrolls to new[j] = j + min(i+1, min_{k<=j}
        # (cand[k-1] - k)) — a parallel prefix-min instead of an O(Th) loop.
        cprime = cand - j1[None, :]
        prefix = jax.lax.associative_scan(jnp.minimum, cprime, axis=1)
        first = jnp.full((b, 1), i + 1, jnp.int32)
        tail = j1[None, :] + jnp.minimum(prefix, i + 1)
        new_row = jnp.concatenate([first, tail], axis=1)
        # rows beyond this batch item's ref length keep their last valid row
        active = (i < rlen)[:, None]
        new_row = jnp.where(active, new_row, row)
        return new_row, None

    final_row, _ = jax.lax.scan(outer, row0, jnp.arange(Tr, dtype=jnp.int32))
    dist = jnp.take_along_axis(final_row, hlen[:, None], axis=1)[:, 0]
    dist = dist.astype(jnp.float32)
    if normalized:
        dist = dist / jnp.maximum(rlen.astype(jnp.float32), 1.0)
    return out(Out=dist[:, None],
               SequenceNum=jnp.asarray(b, jnp.int32))

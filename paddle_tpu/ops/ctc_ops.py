"""CTC: loss (forward-backward) and greedy decoding.

TPU-native replacement for the reference's warp-ctc integration
(/root/reference/paddle/cuda/src/hl_warpctc_wrap.cc dynloads Baidu
warp-ctc; /root/reference/paddle/gserver/layers/WarpCTCLayer.cpp drives
it) and the CTC error evaluator's best-path decoding
(/root/reference/paddle/gserver/evaluators/CTCErrorEvaluator.cpp:60-156).

The loss is the standard log-space alpha recursion over the extended
(blank-interleaved) label sequence, expressed as one ``lax.scan`` over time
with the whole batch vectorized per step — static shapes throughout, so XLA
pipelines the scan body on the VPU. No custom backward is needed: the scan
is reverse-differentiable and ``jax.vjp`` in the generic grad op yields
exactly the classic CTC gradient (the soft alignment posteriors), the same
quantity warp-ctc computes by hand with its beta recursion.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op
from .common import maybe, out, single

_NEG_INF = -1e30


def _log_softmax(x):
    return x - jax.scipy.special.logsumexp(x, axis=-1, keepdims=True)


@register_op("warpctc", optional_inputs=("LogitsLength", "LabelLength"))
def warpctc(attrs, ins):
    """CTC loss per sequence.

    Inputs: Logits [b, T, C] (unnormalized), Label [b, L] int (padded),
    optional LogitsLength [b], LabelLength [b]. Attr ``blank`` (default 0),
    ``norm_by_times`` divides each loss by its logit length
    (WarpCTCLayer.cpp's normByTimes). Output Loss [b, 1].
    """
    logits = single(ins, "Logits")
    label = single(ins, "Label").astype(jnp.int32)
    if label.ndim == 3:
        label = label[..., 0]
    b, T, C = logits.shape
    L = label.shape[1]
    blank = int(attrs.get("blank", 0))
    logit_len = maybe(ins, "LogitsLength")
    label_len = maybe(ins, "LabelLength")
    logit_len = (jnp.full((b,), T, jnp.int32) if logit_len is None
                 else logit_len.reshape(-1).astype(jnp.int32))
    label_len = (jnp.full((b,), L, jnp.int32) if label_len is None
                 else label_len.reshape(-1).astype(jnp.int32))

    logp = _log_softmax(logits.astype(jnp.float32))  # [b, T, C]

    # extended sequence z = [blank, l1, blank, l2, ..., blank], len S = 2L+1
    S = 2 * L + 1
    s_idx = jnp.arange(S)
    z = jnp.where(s_idx % 2 == 0, blank,
                  label[:, jnp.minimum(s_idx // 2, L - 1)])  # [b, S]
    # positions past the true extended length are invalid
    ext_len = 2 * label_len + 1
    valid = s_idx[None, :] < ext_len[:, None]  # [b, S]
    # transition from s-2 allowed iff z[s] != z[s-2] (and s even => blank,
    # which always equals z[s-2] when both blanks — standard CTC rule)
    z_prev2 = jnp.concatenate(
        [jnp.full((b, 2), -1, z.dtype), z[:, :-2]], axis=1)
    skip_ok = (z != z_prev2) & (s_idx[None, :] >= 2)

    # alpha[0]: start in z[0] (blank) or z[1] (first label)
    emit0 = jnp.take_along_axis(logp[:, 0, :], z, axis=1)  # [b, S]
    alpha0 = jnp.where(s_idx[None, :] <= 1, emit0, _NEG_INF)
    alpha0 = jnp.where(valid, alpha0, _NEG_INF)

    def step(alpha, logp_t):
        stay = alpha
        diag = jnp.concatenate(
            [jnp.full((b, 1), _NEG_INF), alpha[:, :-1]], axis=1)
        skip = jnp.concatenate(
            [jnp.full((b, 2), _NEG_INF), alpha[:, :-2]], axis=1)
        skip = jnp.where(skip_ok, skip, _NEG_INF)
        merged = jnp.logaddexp(jnp.logaddexp(stay, diag), skip)
        emit = jnp.take_along_axis(logp_t, z, axis=1)
        new = jnp.where(valid, merged + emit, _NEG_INF)
        return new, new

    # scan over time; gather each sequence's alpha at its own final frame
    _, alphas = jax.lax.scan(step, alpha0, jnp.swapaxes(logp, 0, 1)[1:])
    alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # [T, b, S]
    t_last = jnp.clip(logit_len - 1, 0, T - 1)
    alpha_T = alphas[t_last, jnp.arange(b)]  # [b, S]
    end1 = jnp.take_along_axis(alpha_T, (ext_len - 1)[:, None], axis=1)
    end2 = jnp.take_along_axis(
        alpha_T, jnp.maximum(ext_len - 2, 0)[:, None], axis=1)
    loss = -jnp.logaddexp(end1, end2)[:, 0]  # [b]
    # empty labels: loss = -sum log p(blank) over the frames
    blank_lp = jnp.cumsum(logp[:, :, blank], axis=1)
    empty_loss = -jnp.take_along_axis(blank_lp, t_last[:, None], axis=1)[:, 0]
    loss = jnp.where(label_len == 0, empty_loss, loss)
    if attrs.get("norm_by_times", False):
        loss = loss / jnp.maximum(logit_len.astype(jnp.float32), 1.0)
    return out(Loss=loss[:, None])


@register_op("ctc_greedy_decode", optional_inputs=("LogitsLength",))
def ctc_greedy_decode(attrs, ins):
    """Best-path CTC decoding: per-frame argmax, collapse repeats, drop
    blanks (CTCErrorEvaluator.cpp:60-104's path computation), all with
    static shapes: kept tokens are compacted to the front of a [b, T]
    buffer via a cumsum-position scatter.

    Outputs: Out [b, T] int32 (padded with ``blank``), OutLength [b, 1].
    """
    logits = single(ins, "Logits")
    b, T, C = logits.shape
    blank = int(attrs.get("blank", 0))
    logit_len = maybe(ins, "LogitsLength")
    logit_len = (jnp.full((b,), T, jnp.int32) if logit_len is None
                 else logit_len.reshape(-1).astype(jnp.int32))
    path = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [b, T]
    t_idx = jnp.arange(T)[None, :]
    in_range = t_idx < logit_len[:, None]
    prev = jnp.concatenate(
        [jnp.full((b, 1), -1, path.dtype), path[:, :-1]], axis=1)
    keep = (path != blank) & (path != prev) & in_range  # [b, T]
    pos = jnp.cumsum(keep.astype(jnp.int32), axis=1) - 1  # target slot
    pos = jnp.where(keep, pos, T)  # dropped frames scatter out of range
    dec = jnp.full((b, T), blank, jnp.int32)
    dec = jax.vmap(
        lambda d, p, v: d.at[p].set(v, mode="drop"))(dec, pos, path)
    n = keep.astype(jnp.int32).sum(axis=1)
    return {"Out": [dec], "OutLength": [n[:, None]]}

"""Recurrent ops: LSTM / GRU cells and full scans.

TPU-native replacement for the reference's recurrent machinery:
- fused CUDA cells   /root/reference/paddle/operators/math/detail/
  lstm_gpu_kernel.h, gru_gpu_kernel.h (+ legacy hl_cuda_lstm.cu)
- batch reordering   /root/reference/paddle/operators/math/sequence2batch.h
  (reorders concatenated LoD rows into time-major batches so each timestep is
  one GEMM)
- the ops            /root/reference/paddle/operators/lstm_op.cc, gru_op.cc,
  lstm_unit_op.cc, gru_unit_op.cc

Design: inputs are already dense-padded [batch, T, ...] (see sequence_ops),
so no sequence2batch reordering exists at all — a transpose to time-major +
``jax.lax.scan`` gives XLA one fused while-loop whose body is a single
[b, h] x [h, gates*h] MXU matmul plus elementwise gate math (which XLA fuses
into the matmul's epilogue). Finished rows (t >= Length[b]) carry their state
through unchanged and emit zeros, reproducing LoD semantics.

Gate layouts follow the reference:
- LSTM Weight [h, 4h] ordered (candidate, input, forget, output) — the
  reference's {W_ch, W_ih, W_fh, W_oh} (lstm_op.cc:125-135); optional
  peephole weights (W_ic, W_fc, W_oc) live in Bias columns 4h:7h.
- GRU  Weight [h, 3h]: columns [0:2h] = (update, reset) gates, [2h:3h] =
  candidate; Bias [1, 3h]; h' = (1-u)*h + u*candidate (gru_op.cc:142).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op
from . import common
from .common import maybe, out, single
from .sequence_ops import time_mask

_ACT = {
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "relu": jax.nn.relu,
    "identity": lambda x: x,
}


def _lstm_step(h, c, gates, bias, peep, act_g, act_cand, act_cell):
    """One LSTM step. gates: [b, 4h] = x_proj + h @ W (pre-activation),
    columns ordered (candidate, input, forget, output) per lstm_op.cc.
    ``act_cand`` acts on the candidate gate, ``act_cell`` on the cell state
    in h = o * act_cell(c) (lstm_op.h:106-111)."""
    hdim = h.shape[-1]
    if bias is not None:
        gates = gates + bias[..., : 4 * hdim]
    gc, gi, gf, go = jnp.split(gates, 4, axis=-1)
    if peep is not None:
        wic, wfc, woc = jnp.split(peep, 3, axis=-1)
        gi = gi + wic * c
        gf = gf + wfc * c
    i = act_g(gi)
    f = act_g(gf)
    c_new = f * c + i * act_cand(gc)
    if peep is not None:
        go = go + woc * c_new
    o = act_g(go)
    h_new = o * act_cell(c_new)
    return h_new, c_new


@register_op("lstm", optional_inputs=("Bias", "H0", "C0", "Length"))
def lstm(attrs, ins):
    """Full LSTM scan (reference lstm_op.cc `dynamic_lstm`).

    Input: [b, T, 4h] pre-projected x (the layer does x @ Wx outside the
    recurrence as ONE big [b*T, d] x [d, 4h] matmul — time-parallel on the
    MXU; only the h-recurrence is sequential).
    """
    x = single(ins, "Input")  # [b, T, 4h]
    w = single(ins, "Weight")  # [h, 4h]
    bias = maybe(ins, "Bias")  # [1, 4h] or [1, 7h] w/ peepholes
    lengths = maybe(ins, "Length")
    h0 = maybe(ins, "H0")
    c0 = maybe(ins, "C0")
    b, T, four_h = x.shape
    hdim = four_h // 4
    use_peep = attrs.get("use_peepholes", False)
    reverse = attrs.get("is_reverse", False)
    act_g = _ACT[attrs.get("gate_activation", "sigmoid")]
    act_cand = _ACT[attrs.get("candidate_activation", "tanh")]
    act_cell = _ACT[attrs.get("cell_activation", "tanh")]

    peep = None
    if bias is not None and use_peep:
        peep = bias[..., 4 * hdim: 7 * hdim]
    h = h0 if h0 is not None else jnp.zeros((b, hdim), x.dtype)
    c = c0 if c0 is not None else jnp.zeros((b, hdim), x.dtype)

    xs = jnp.swapaxes(x, 0, 1)  # [T, b, 4h]
    mask = (jnp.swapaxes(time_mask(lengths, T, x.dtype), 0, 1)[..., None]
            if lengths is not None else None)

    x_cast, w_cast = common.amp_cast(xs, w)

    def step(carry, inp):
        h, c = carry
        if mask is None:
            xt, m = inp, None
        else:
            xt, m = inp
        gates = xt + jnp.dot(common.amp_cast(h), w_cast,
                             precision=common.mxu_precision()).astype(h.dtype)
        h_new, c_new = _lstm_step(h, c, gates, bias, peep, act_g, act_cand,
                                  act_cell)
        if m is not None:
            h_new = m * h_new + (1 - m) * h
            c_new = m * c_new + (1 - m) * c
            y = (h_new * m, c_new * m)
        else:
            y = (h_new, c_new)
        return (h_new, c_new), y

    seq = x_cast if mask is None else (x_cast, mask)
    (h, c), (ys, cs) = jax.lax.scan(step, (h, c), seq, reverse=reverse)
    hidden = jnp.swapaxes(ys, 0, 1)  # [b, T, h]
    cell = jnp.swapaxes(cs, 0, 1)
    return out(Hidden=hidden, Cell=cell, LastH=h, LastC=c)


@register_op("gru", optional_inputs=("Bias", "H0", "Length"))
def gru(attrs, ins):
    """Full GRU scan (reference gru_op.cc `dynamic_gru`).

    Input: [b, T, 3h] pre-projected x. Reference formulas (gru_op.cc:142):
    m = act(x_m + (r . h) @ W_m); h' = (1-u)*h + u*m.
    """
    x = single(ins, "Input")  # [b, T, 3h]
    w = single(ins, "Weight")  # [h, 3h]: [:, :2h] gates, [:, 2h:] candidate
    bias = maybe(ins, "Bias")
    lengths = maybe(ins, "Length")
    h0 = maybe(ins, "H0")
    b, T, three_h = x.shape
    hdim = three_h // 3
    reverse = attrs.get("is_reverse", False)
    act_g = _ACT[attrs.get("gate_activation", "sigmoid")]
    act_c = _ACT[attrs.get("activation", "tanh")]

    h = h0 if h0 is not None else jnp.zeros((b, hdim), x.dtype)

    xs = jnp.swapaxes(x, 0, 1)
    if bias is not None:
        xs = xs + bias
    mask = (jnp.swapaxes(time_mask(lengths, T, x.dtype), 0, 1)[..., None]
            if lengths is not None else None)
    prec = common.mxu_precision()
    xs, wg, wc = common.amp_cast(xs, w[:, : 2 * hdim], w[:, 2 * hdim:])

    def step(h, inp):
        if mask is None:
            xt, m = inp, None
        else:
            xt, m = inp
        xg, xc = xt[..., : 2 * hdim], xt[..., 2 * hdim:]
        g = act_g(xg + jnp.dot(common.amp_cast(h), wg,
                               precision=prec).astype(h.dtype))
        u, r = g[..., :hdim], g[..., hdim:]
        cand = act_c(xc + jnp.dot(common.amp_cast(r * h), wc,
                                  precision=prec).astype(h.dtype))
        h_new = (1.0 - u) * h + u * cand
        if m is not None:
            h_new = m * h_new + (1 - m) * h
            y = h_new * m
        else:
            y = h_new
        return h_new, y

    seq = xs if mask is None else (xs, mask)
    h, ys = jax.lax.scan(step, h, seq, reverse=reverse)
    return out(Hidden=jnp.swapaxes(ys, 0, 1), LastH=h)


@register_op("simple_rnn", optional_inputs=("Bias", "H0", "Length"))
def simple_rnn(attrs, ins):
    """Plain recurrent layer (reference gserver RecurrentLayer.cpp, the v1
    ``recurrent_layer``): out_t = act(in_t + out_{t-1} @ W + b). ``Input``
    is [b, T, h] ALREADY at hidden width (the v1 contract: the projection
    into the layer happens outside, e.g. via mixed_layer); only the h@W
    recurrence is sequential."""
    x = single(ins, "Input")  # [b, T, h]
    w = single(ins, "Weight")  # [h, h]
    bias = maybe(ins, "Bias")
    lengths = maybe(ins, "Length")
    h0 = maybe(ins, "H0")
    b, T, hdim = x.shape
    reverse = attrs.get("is_reverse", False)
    act = _ACT[attrs.get("activation", "tanh")]

    h = h0 if h0 is not None else jnp.zeros((b, hdim), x.dtype)
    xs = jnp.swapaxes(x, 0, 1)  # [T, b, h]
    if bias is not None:
        xs = xs + bias
    mask = (jnp.swapaxes(time_mask(lengths, T, x.dtype), 0, 1)[..., None]
            if lengths is not None else None)
    prec = common.mxu_precision()
    xs, w_cast = common.amp_cast(xs, w)

    def step(h, inp):
        if mask is None:
            xt, m = inp, None
        else:
            xt, m = inp
        h_new = act(xt + jnp.dot(common.amp_cast(h), w_cast,
                                 precision=prec).astype(h.dtype))
        if m is not None:
            h_new = m * h_new + (1 - m) * h
            y = h_new * m
        else:
            y = h_new
        return h_new, y

    seq = xs if mask is None else (xs, mask)
    h, ys = jax.lax.scan(step, h, seq, reverse=reverse)
    return out(Hidden=jnp.swapaxes(ys, 0, 1), LastH=h)


@register_op("lstm_unit", optional_inputs=("Bias",))
def lstm_unit(attrs, ins):
    """Single LSTM step (lstm_unit_op.cc): gates already projected, [b, 4h]."""
    gates = single(ins, "X")
    c_prev = single(ins, "C_prev")
    bias = maybe(ins, "Bias")
    forget_bias = attrs.get("forget_bias", 0.0)
    hdim = c_prev.shape[-1]
    if bias is not None:
        gates = gates + bias
    # gate layout (i, f, o, g) matches lstm_unit_op.h:63-66
    gi, gf, go, gc = jnp.split(gates, 4, axis=-1)
    i = jax.nn.sigmoid(gi)
    f = jax.nn.sigmoid(gf + forget_bias)
    c = f * c_prev + i * jnp.tanh(gc)
    h = jax.nn.sigmoid(go) * jnp.tanh(c)
    return out(C=c, H=h)


@register_op("gru_unit", optional_inputs=("Bias",))
def gru_unit(attrs, ins):
    """Single GRU step (gru_unit_op.cc): Input [b, 3h] pre-projected."""
    xt = single(ins, "Input")
    h_prev = single(ins, "HiddenPrev")
    w = single(ins, "Weight")  # [h, 3h]
    bias = maybe(ins, "Bias")
    act_g = _ACT[attrs.get("gate_activation", "sigmoid")]
    act_c = _ACT[attrs.get("activation", "tanh")]
    hdim = h_prev.shape[-1]
    if bias is not None:
        xt = xt + bias
    prec = common.mxu_precision()
    xg, xc = xt[..., : 2 * hdim], xt[..., 2 * hdim:]
    g = act_g(xg + jnp.dot(h_prev, w[:, : 2 * hdim], precision=prec))
    u, r = g[..., :hdim], g[..., hdim:]
    cand = act_c(xc + jnp.dot(r * h_prev, w[:, 2 * hdim:], precision=prec))
    h = (1.0 - u) * h_prev + u * cand  # gru_unit_op.cc:122
    return out(Hidden=h, Gate=g, ResetHiddenPrev=r * h_prev)

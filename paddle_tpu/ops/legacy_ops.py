"""Legacy gserver layer-type tail as ops.

The reference's v1 engine registers 105 layer types
(/root/reference/paddle/gserver/layers/); most map onto existing fluid-style
ops here. This module covers the remaining small-but-real ones so the DSL
surface is complete: per-row arithmetic combinators, feature-dim reshapes,
ranking/feature-cross pieces, and sampling. Each docstring cites the
gserver (or fluid operators/) source it matches. All are pure VPU-friendly
jnp formulations — elementwise/reduction work XLA fuses into neighbours.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op
from .common import maybe, out, single


def _row_scalar(w):
    """[b], [b,1] -> [b,1] broadcastable row scalar."""
    return w.reshape(w.shape[0], 1)


@register_op("interpolation")
def interpolation(attrs, ins):
    """out = w*x + (1-w)*y with per-row scalar w
    (InterpolationLayer.cpp)."""
    w = _row_scalar(single(ins, "W"))
    x, y = single(ins, "X"), single(ins, "Y")
    return out(Out=w * x + (1.0 - w) * y)


@register_op("scaling")
def scaling(attrs, ins):
    """out_i = w_i * x_i, per-row scalar times row (ScalingLayer.cpp)."""
    return out(Out=_row_scalar(single(ins, "W")) * single(ins, "X"))


@register_op("power")
def power(attrs, ins):
    """out_i = x_i ^ w_i with per-row scalar exponent (PowerLayer.cpp)."""
    return out(Out=single(ins, "X") ** _row_scalar(single(ins, "W")))


@register_op("slope_intercept")
def slope_intercept(attrs, ins):
    """out = slope*x + intercept (SlopeInterceptLayer.cpp)."""
    return out(Out=float(attrs.get("slope", 1.0)) * single(ins, "X")
               + float(attrs.get("intercept", 0.0)))


@register_op("addto", optional_inputs=("Bias",))
def addto(attrs, ins):
    """Elementwise sum of N same-shaped inputs (+bias) (AddtoLayer.cpp)."""
    xs = ins["X"]
    acc = xs[0]
    for x in xs[1:]:
        acc = acc + x
    b = maybe(ins, "Bias")
    if b is not None:
        acc = acc + b
    return out(Out=acc)


@register_op("sum_to_one_norm")
def sum_to_one_norm(attrs, ins):
    """Row-normalize to sum 1 (SumToOneNormLayer.cpp)."""
    x = single(ins, "X")
    s = jnp.sum(x, axis=-1, keepdims=True)
    return out(Out=x / jnp.where(jnp.abs(s) < 1e-12, 1.0, s))


@register_op("row_l2_norm")
def row_l2_norm(attrs, ins):
    """Row-normalize to unit L2 (RowL2NormLayer.cpp)."""
    x = single(ins, "X")
    n = jnp.sqrt(jnp.sum(x * x, axis=-1, keepdims=True))
    return out(Out=x / jnp.maximum(n, 1e-12))


@register_op("scale_shift")
def scale_shift(attrs, ins):
    """y = w*x + b with LEARNED scalar w (and b) (ScaleShiftLayer.cpp)."""
    x = single(ins, "X")
    w = single(ins, "Scale").reshape(())
    b = maybe(ins, "Bias")
    y = w * x
    if b is not None:
        y = y + b.reshape(())
    return out(Out=y)


@register_op("linear_comb")
def linear_comb(attrs, ins):
    """out[b] = sum_i w[b,i] * x[b, i*d:(i+1)*d]  (LinearChainCombLayer /
    linear_comb_layer: weighted sum of m d-dim sub-vectors)."""
    w = single(ins, "W")     # [b, m]
    x = single(ins, "X")     # [b, m*d]
    b_, m = w.shape
    d = x.shape[-1] // m
    return out(Out=jnp.einsum("bm,bmd->bd", w, x.reshape(b_, m, d)))


@register_op("dot_prod")
def dot_prod(attrs, ins):
    """Row-wise dot product -> [b, 1] (DotProdLayer.cpp)."""
    x, y = single(ins, "X"), single(ins, "Y")
    return out(Out=jnp.sum(x * y, axis=-1, keepdims=True))


@register_op("out_prod")
def out_prod(attrs, ins):
    """Row-wise outer product -> [b, dx*dy] (OuterProdLayer.cpp)."""
    x, y = single(ins, "X"), single(ins, "Y")
    o = jnp.einsum("bi,bj->bij", x, y)
    return out(Out=o.reshape(x.shape[0], -1))


@register_op("l2_distance")
def l2_distance(attrs, ins):
    """Row-wise euclidean distance -> [b, 1] (L2DistanceLayer.cpp)."""
    d = single(ins, "X") - single(ins, "Y")
    return out(Out=jnp.sqrt(jnp.maximum(
        jnp.sum(d * d, axis=-1, keepdims=True), 1e-12)))


@register_op("repeat")
def repeat(attrs, ins):
    """Repeat features along the last dim (FeatureMapExpandLayer /
    repeat_layer). ``as_row_vector``: True tiles [a b] -> [a b a b],
    False repeats elementwise [a b] -> [a a b b]."""
    x = single(ins, "X")
    n = int(attrs.get("num_repeats", 1))
    if attrs.get("as_row_vector", True):
        return out(Out=jnp.tile(x, (1,) * (x.ndim - 1) + (n,)))
    return out(Out=jnp.repeat(x, n, axis=-1))


@register_op("resize")
def resize(attrs, ins):
    """Reinterpret rows with a new feature width (ResizeLayer.cpp):
    [b, d] -> [b*d/size, size]."""
    x = single(ins, "X")
    size = int(attrs["size"])
    return out(Out=x.reshape(-1, size))


@register_op("rotate")
def rotate(attrs, ins):
    """Rotate each sample's [H, W] feature grid by 90 degrees CCW
    (RotateLayer.cpp)."""
    x = single(ins, "X")
    h, w = int(attrs["height"]), int(attrs["width"])
    b = x.shape[0]
    g = x.reshape(b, h, w, -1)
    g = jnp.flip(jnp.swapaxes(g, 1, 2), axis=1)
    return out(Out=g.reshape(b, -1) if x.ndim == 2 else g)


@register_op("multiplex")
def multiplex(attrs, ins):
    """Row-wise select among N candidate tensors by index
    (/root/reference/paddle/operators/multiplex_op.cc): out[r] =
    X[ids[r]][r]."""
    ids = single(ins, "Ids").reshape(-1).astype(jnp.int32)
    xs = jnp.stack(ins["X"], axis=0)           # [n, b, d]
    rows = jnp.arange(xs.shape[1])
    return out(Out=xs[ids, rows])


@register_op("kmax_seq_score", optional_inputs=("Length",))
def kmax_seq_score(attrs, ins):
    """Top-k score positions per sequence (KmaxSeqScoreLayer.cpp): scores
    [b, T] (+ valid lengths) -> indices [b, k]."""
    scores = single(ins, "X")
    if scores.ndim == 3:
        scores = scores[..., 0]
    k = int(attrs.get("beam_size", 1))
    length = maybe(ins, "Length")
    if length is not None:
        t = jnp.arange(scores.shape[1])[None, :]
        scores = jnp.where(t < length.reshape(-1, 1), scores, -jnp.inf)
    _, idx = jax.lax.top_k(scores, k)
    return out(Out=idx.astype(jnp.int64))


@register_op("sequence_reshape")
def sequence_reshape(attrs, ins):
    """Change the feature width, folding the factor into the time dim
    (/root/reference/paddle/operators/sequence_reshape_op.cc):
    [b, T, d] -> [b, T*d/new_dim, new_dim]."""
    x = single(ins, "X")
    new_dim = int(attrs["new_dim"])
    b, t, d = x.shape
    return out(Out=x.reshape(b, t * d // new_dim, new_dim))


@register_op("sampling_id", needs_rng=True)
def sampling_id(attrs, ins, rng=None):
    """Sample a column index per row from probability rows
    (/root/reference/paddle/operators/... SamplingIdLayer.cpp)."""
    p = single(ins, "X")
    ids = jax.random.categorical(rng, jnp.log(jnp.maximum(p, 1e-20)),
                                 axis=-1)
    return out(Out=ids.astype(jnp.int64))


@register_op("factorization_machine")
def factorization_machine(attrs, ins):
    """Second-order FM term (FactorizationMachineLayer.cpp):
    0.5 * sum_f [ (x V)_f^2 - (x^2 V^2)_f ]  -> [b, 1]."""
    x = single(ins, "X")        # [b, d]
    v = single(ins, "V")        # [d, f]
    xv = x @ v
    x2v2 = (x * x) @ (v * v)
    return out(Out=0.5 * jnp.sum(xv * xv - x2v2, axis=-1, keepdims=True))


@register_op("gated_unit")
def gated_unit(attrs, ins):
    """Gated linear unit over precomputed projections
    (GatedRecurrentLayer-adjacent gated_unit_layer): out = act(P) *
    sigmoid(G)."""
    p, g = single(ins, "P"), single(ins, "G")
    act = attrs.get("act", "tanh")
    if act == "tanh":
        p = jnp.tanh(p)
    elif act == "relu":
        p = jnp.maximum(p, 0)
    elif act not in (None, "", "identity", "linear"):
        raise ValueError(f"gated_unit: unsupported act {act!r}")
    return out(Out=p * jax.nn.sigmoid(g))

"""Remaining op-zoo parity: the reference ops not covered by the core
families (audited against REGISTER_OP in /root/reference/paddle/operators).

Notes on deliberate non-ports:
- *_cudnn variants are aliases here: there is no per-library kernel choice
  (operator.cc:482-540 kKernelPriority) — XLA picks the TPU lowering.
- ncclAllReduce/ncclBcast/ncclReduce have no op-level equivalent BY DESIGN:
  all communication is GSPMD-inserted collectives (SURVEY.md §5.8);
  user programs never contain communication ops.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import get_op, register_op
from .common import maybe, mxu_precision, out, single
from .sequence_ops import time_mask


# ---------------------------------------------------------------------------
# Elementwise / small math
# ---------------------------------------------------------------------------
@register_op("fill_zeros_like")
def fill_zeros_like(attrs, ins):
    return out(Y=jnp.zeros_like(single(ins, "X")))


@register_op("is_empty")
def is_empty(attrs, ins):
    x = single(ins, "X")
    return out(Out=jnp.asarray(x.size == 0))


@register_op("l1_norm")
def l1_norm(attrs, ins):
    return out(Out=jnp.sum(jnp.abs(single(ins, "X"))).reshape(()))


@register_op("norm")
def norm(attrs, ins):
    """L2 (Frobenius) norm, norm_op.cc."""
    x = single(ins, "X")
    return out(Out=jnp.sqrt(jnp.sum(x * x)).reshape(()))


@register_op("soft_relu")
def soft_relu(attrs, ins):
    t = attrs.get("threshold", 40.0)
    x = jnp.clip(single(ins, "X"), -t, t)
    return out(Out=jnp.log1p(jnp.exp(x)))


@register_op("modified_huber_loss")
def modified_huber_loss(attrs, ins):
    """modified_huber_loss_op.cc: binary classification with y in {0,1};
    z = 2y-1 margin loss."""
    x = single(ins, "X").reshape(-1)
    y = single(ins, "Y").reshape(-1).astype(x.dtype)
    z = (2.0 * y - 1.0) * x
    loss = jnp.where(z < -1.0, -4.0 * z,
                     jnp.where(z < 1.0, (1.0 - z) ** 2, 0.0))
    return out(Out=loss[:, None], IntermediateVal=z[:, None])


@register_op("scatter")
def scatter(attrs, ins):
    """scatter_op.cc: Out = X with rows at Ids replaced by (or accumulated
    with) Updates."""
    x = single(ins, "X")
    ids = single(ins, "Ids").reshape(-1).astype(jnp.int32)
    upd = single(ins, "Updates")
    if attrs.get("overwrite", True):
        return out(Out=x.at[ids].set(upd))
    return out(Out=x.at[ids].add(upd))


@register_op("bilinear_tensor_product", optional_inputs=("Bias",))
def bilinear_tensor_product(attrs, ins):
    """out[:, k] = x W_k y^T + b (bilinear_tensor_product_op.cc);
    Weight [K, dx, dy]."""
    x = single(ins, "X")  # [b, dx]
    y = single(ins, "Y")  # [b, dy]
    w = single(ins, "Weight")  # [K, dx, dy]
    bias = maybe(ins, "Bias")
    o = jnp.einsum("bi,kij,bj->bk", x, w, y)
    if bias is not None:
        o = o + bias
    return out(Out=o)


@register_op("conv_shift")
def conv_shift(attrs, ins):
    """Circular correlation (conv_shift_op.cc): Y's width is odd (2m+1);
    out[i, j] = sum_k x[i, (j + k - m) mod W] * y[i, k]."""
    x = single(ins, "X")  # [b, W]
    y = single(ins, "Y")  # [b, 2m+1]
    W = x.shape[1]
    m = y.shape[1] // 2
    cols = [jnp.roll(x, m - k, axis=1) * y[:, k: k + 1]
            for k in range(y.shape[1])]
    return out(Out=sum(cols))


# ---------------------------------------------------------------------------
# 3-D conv/pool family + index pooling + unpool + spp
# ---------------------------------------------------------------------------
def _triple(v):
    return (v, v, v) if isinstance(v, int) else tuple(v)


@register_op("conv3d")
def conv3d(attrs, ins):
    x = single(ins, "Input")  # NCDHW (reference layout)
    w = single(ins, "Filter")  # [out_c, in_c/g, kd, kh, kw]
    strides = _triple(attrs.get("strides", 1))
    pads = _triple(attrs.get("paddings", 0))
    dil = _triple(attrs.get("dilations", 1))
    y = jax.lax.conv_general_dilated(
        x, w, window_strides=strides,
        padding=[(p, p) for p in pads], rhs_dilation=dil,
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
        feature_group_count=attrs.get("groups", 1),
        precision=mxu_precision())
    return out(Output=y)


@register_op("conv3d_transpose")
def conv3d_transpose(attrs, ins):
    x = single(ins, "Input")
    w = single(ins, "Filter")  # [in_c, out_c, kd, kh, kw]
    strides = _triple(attrs.get("strides", 1))
    pads = _triple(attrs.get("paddings", 0))
    dil = _triple(attrs.get("dilations", 1))
    k = w.shape[2:]
    pad = [(d * (kk - 1) - p, d * (kk - 1) - p)
           for kk, p, d in zip(k, pads, dil)]
    # transpose conv = fractionally-strided conv with the spatially-flipped
    # kernel ("IODHW" handles the in/out channel swap)
    w_flip = w[:, :, ::-1, ::-1, ::-1]
    y = jax.lax.conv_general_dilated(
        x, w_flip, window_strides=(1, 1, 1), padding=pad,
        lhs_dilation=strides, rhs_dilation=dil,
        dimension_numbers=("NCDHW", "IODHW", "NCDHW"),
        feature_group_count=attrs.get("groups", 1))
    return out(Output=y)


@register_op("pool3d")
def pool3d(attrs, ins):
    x = single(ins, "X")  # NCDHW
    ptype = attrs.get("pooling_type", "max")
    ksize = _triple(attrs.get("ksize", 2))
    strides = _triple(attrs.get("strides", 1))
    pads = _triple(attrs.get("paddings", 0))
    window = (1, 1) + ksize
    stride = (1, 1) + strides
    padding = [(0, 0), (0, 0)] + [(p, p) for p in pads]
    if attrs.get("global_pooling", False):
        window = (1, 1) + x.shape[2:]
        stride = (1,) * 5
        padding = [(0, 0)] * 5
    if ptype == "max":
        y = jax.lax.reduce_window(x, -np.inf, jax.lax.max, window, stride,
                                  padding)
    else:
        s = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, stride,
                                  padding)
        y = s / float(np.prod(window))  # actual window volume, not ksize
    return out(Out=y)


def _max_pool_with_index(x, ksize, strides, pads, spatial_dims):
    """Max pooling that also returns flat spatial argmax indices (the
    reference's max_pool{2,3}d_with_index, consumed by unpool)."""
    spatial = x.shape[2:]
    flat_idx = jnp.arange(int(np.prod(spatial)), dtype=jnp.int32).reshape(
        (1, 1) + spatial)
    flat_idx = jnp.broadcast_to(flat_idx, x.shape)
    window = (1, 1) + ksize
    stride = (1, 1) + strides
    padding = [(0, 0), (0, 0)] + [(p, p) for p in pads]

    def reducer(a, b):
        av, ai = a
        bv, bi = b
        take_b = bv > av
        return jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai)

    init = (jnp.asarray(-np.inf, x.dtype), jnp.asarray(-1, jnp.int32))
    y, idx = jax.lax.reduce_window((x, flat_idx), init, reducer, window,
                                   stride, padding)
    return y, idx


@register_op("max_pool2d_with_index")
def max_pool2d_with_index(attrs, ins):
    x = single(ins, "X")  # NCHW
    k = attrs.get("ksize", [2, 2])
    s = attrs.get("strides", [1, 1])
    p = attrs.get("paddings", [0, 0])
    y, idx = _max_pool_with_index(x, tuple(k), tuple(s), tuple(p), 2)
    return out(Out=y, Mask=idx)


@register_op("max_pool3d_with_index")
def max_pool3d_with_index(attrs, ins):
    x = single(ins, "X")  # NCDHW
    k = _triple(attrs.get("ksize", 2))
    s = _triple(attrs.get("strides", 1))
    p = _triple(attrs.get("paddings", 0))
    y, idx = _max_pool_with_index(x, k, s, p, 3)
    return out(Out=y, Mask=idx)


@register_op("unpool")
def unpool(attrs, ins):
    """unpool_op.cc: scatter pooled values back to the argmax positions
    recorded by max_pool2d_with_index."""
    x = single(ins, "X")  # [n, c, ph, pw]
    idx = single(ins, "Indices").astype(jnp.int32)
    oh, ow = attrs["unpooled_height"], attrs["unpooled_width"]
    n, c = x.shape[:2]
    flat = jnp.zeros((n, c, oh * ow), x.dtype)
    xi = x.reshape(n, c, -1)
    ii = idx.reshape(n, c, -1)
    flat = jax.vmap(jax.vmap(lambda f, v, i: f.at[i].add(v)))(flat, xi, ii)
    return out(Out=flat.reshape(n, c, oh, ow))


@register_op("spp")
def spp(attrs, ins):
    """Spatial pyramid pooling (spp_op.cc): concat flattened max pools at
    pyramid levels 0..L-1 (level l = 2^l x 2^l bins)."""
    x = single(ins, "X")  # NCHW
    levels = attrs.get("pyramid_height", 3)
    n, c, h, w = x.shape
    feats = []
    for l in range(levels):
        bins = 2 ** l
        kh, kw = -(-h // bins), -(-w // bins)  # ceil
        ph, pw = kh * bins - h, kw * bins - w
        y = jax.lax.reduce_window(
            x, -np.inf, jax.lax.max, (1, 1, kh, kw), (1, 1, kh, kw),
            [(0, 0), (0, 0), (0, ph), (0, pw)])
        # a window can fall entirely in padding (ceil rounding): zero it
        y = jnp.where(jnp.isfinite(y), y, 0.0)
        feats.append(y.reshape(n, -1))
    return out(Out=jnp.concatenate(feats, axis=1))


@register_op("roi_pool")
def roi_pool(attrs, ins):
    """roi_pool_op.cc: max-pool each ROI into a fixed [ph, pw] grid.
    ROIs [R, 5] = (batch_idx, x1, y1, x2, y2) in spatial_scale units."""
    x = single(ins, "X")  # [N, C, H, W]
    rois = single(ins, "ROIs")
    ph = attrs.get("pooled_height", 2)
    pw = attrs.get("pooled_width", 2)
    scale = attrs.get("spatial_scale", 1.0)
    N, C, H, W = x.shape
    ys = jnp.arange(H, dtype=jnp.float32)
    xs = jnp.arange(W, dtype=jnp.float32)

    def one_roi(roi):
        b = roi[0].astype(jnp.int32)
        x1, y1, x2, y2 = jnp.round(roi[1:] * scale)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        img = x[b]  # [C, H, W]
        outs = []
        for i in range(ph):
            for j in range(pw):
                hs = y1 + jnp.floor(i * rh / ph)
                he = y1 + jnp.ceil((i + 1) * rh / ph)
                ws = x1 + jnp.floor(j * rw / pw)
                we = x1 + jnp.ceil((j + 1) * rw / pw)
                m = ((ys >= hs) & (ys < he))[None, :, None] & \
                    ((xs >= ws) & (xs < we))[None, None, :]
                cell = jnp.where(m, img, -jnp.inf).max(axis=(1, 2))
                outs.append(jnp.where(jnp.isfinite(cell), cell, 0.0))
        return jnp.stack(outs, axis=1).reshape(C, ph, pw)

    return out(Out=jax.vmap(one_roi)(rois.astype(jnp.float32)))


# ---------------------------------------------------------------------------
# Sequence / LoD leftovers
# ---------------------------------------------------------------------------
@register_op("lod_reset", optional_inputs=("Length", "TargetLength"))
def lod_reset(attrs, ins):
    """lod_reset_op.cc: data unchanged, lengths replaced (dense+mask form:
    pass-through X with the new Length vector as OutLength)."""
    x = single(ins, "X")
    tgt = maybe(ins, "TargetLength")
    if tgt is None:
        tgt = jnp.asarray(attrs["target_lengths"], jnp.int32)
    return out(Out=x, OutLength=tgt.astype(jnp.int32))


@register_op("sequence_slice", optional_inputs=("Length",))
def sequence_slice(attrs, ins):
    """sequence_slice_op.cc: per-row [offset, offset+length) window; rows
    shift to the front, remainder zeroed."""
    x = single(ins, "X")  # [b, T, ...]
    offset = single(ins, "Offset").reshape(-1).astype(jnp.int32)  # [b]
    length = single(ins, "SliceLength").reshape(-1).astype(jnp.int32)  # [b]
    T = x.shape[1]
    t = jnp.arange(T, dtype=jnp.int32)[None, :]
    src = jnp.clip(offset[:, None] + t, 0, T - 1)
    src = src.reshape(src.shape + (1,) * (x.ndim - 2))
    y = jnp.take_along_axis(x, src, axis=1)
    mask = (t < length[:, None]).reshape(
        (x.shape[0], T) + (1,) * (x.ndim - 2))
    return out(Out=y * mask.astype(x.dtype), OutLength=length)


@register_op("beam_search")
def beam_search(attrs, ins):
    """One beam-search step (beam_search_op.cc): prune beam*V candidates to
    the top beam_size. Inputs: PreIds [b, beam], PreScores [b, beam],
    Scores [b, beam, V] (log-probs of next token). Outputs SelectedIds,
    SelectedScores, ParentIdx [b, beam]."""
    pre_scores = single(ins, "PreScores")
    scores = single(ins, "Scores")
    beam = int(attrs.get("beam_size", scores.shape[1]))
    eos = int(attrs.get("end_id", 1))
    pre_ids = single(ins, "PreIds")
    b, cur_beam, V = scores.shape
    finished = pre_ids == eos
    eos_only = jnp.full((V,), -jnp.inf).at[eos].set(0.0)
    cand = jnp.where(finished[..., None], eos_only[None, None, :], scores)
    total = pre_scores[..., None] + cand
    top, idx = jax.lax.top_k(total.reshape(b, cur_beam * V), beam)
    return out(SelectedIds=(idx % V).astype(jnp.int64),
               SelectedScores=top,
               ParentIdx=(idx // V).astype(jnp.int64))


# ---------------------------------------------------------------------------
# Losses / sampling / metrics
# ---------------------------------------------------------------------------
def _nce_grad(attrs, ins, outs, ogs):
    """Deterministic NCE gradient given the sampled ids recorded in the
    forward outputs (so the same noise samples are differentiated —
    nce_op.h grad kernel)."""
    x = ins["Input"][0]
    w = ins["Weight"][0]
    bias = ins.get("Bias", [None])[0]
    logits = outs["SampleLogits"][0]
    ids = outs["SampleLabels"][0].astype(jnp.int32)
    dcost = ogs["Cost"][0]  # [b, 1]
    k1 = logits.shape[1]
    targets = jnp.zeros_like(logits).at[:, 0].set(1.0)
    dlogits = (jax.nn.sigmoid(logits) - targets) / k1 * dcost  # [b, 1+k]
    dx = jnp.einsum("bk,bkd->bd", dlogits, w[ids])
    dw = jnp.zeros_like(w).at[ids].add(
        jnp.einsum("bk,bd->bkd", dlogits, x))
    result = {"Input": [dx], "Weight": [dw], "Label": [None]}
    if bias is not None:
        db = jnp.zeros_like(bias.reshape(-1)).at[ids.reshape(-1)].add(
            dlogits.reshape(-1))
        result["Bias"] = [db.reshape(bias.shape)]
    return result


@register_op("nce", needs_rng=True, grad_fn=_nce_grad,
             optional_inputs=("Bias", "SampleWeight"))
def nce(attrs, ins, rng):
    """Noise-contrastive estimation loss (nce_op.cc): binary logistic over
    the true class + k uniform negative samples — the sampled-softmax
    training path for huge output vocabularies (the dense-softmax
    alternative the sparse pserver served in the reference)."""
    x = single(ins, "Input")  # [b, d]
    label = single(ins, "Label").reshape(-1).astype(jnp.int32)  # [b]
    w = single(ins, "Weight")  # [V, d]
    bias = maybe(ins, "Bias")
    k = int(attrs.get("num_neg_samples", 10))
    V = w.shape[0]
    b = x.shape[0]
    neg = jax.random.randint(rng, (b, k), 0, V)  # uniform sampler
    ids = jnp.concatenate([label[:, None], neg], axis=1)  # [b, 1+k]
    wsel = w[ids]  # [b, 1+k, d]
    logits = jnp.einsum("bkd,bd->bk", wsel, x)
    if bias is not None:
        logits = logits + bias.reshape(-1)[ids]
    # logistic: true sample label 1, noise 0; subtract log(k/V) prior
    logits = logits - jnp.log(k / V)
    targets = jnp.zeros_like(logits).at[:, 0].set(1.0)
    loss = jnp.mean(
        jnp.maximum(logits, 0) - logits * targets
        + jnp.log1p(jnp.exp(-jnp.abs(logits))), axis=1, keepdims=True)
    return out(Cost=loss, SampleLogits=logits,
               SampleLabels=ids.astype(jnp.int64))


@register_op("precision_recall")
def precision_recall(attrs, ins):
    """Batch precision/recall/F1 per class + macro avg
    (precision_recall_op.cc / legacy PrecisionRecallEvaluator)."""
    cc = get_op("confusion_counts")
    counts = cc.fn({"num_classes": attrs["num_classes"]},
                   {"Pred": ins["Pred"], "Label": ins["Label"]})
    tp = counts["TP"][0].astype(jnp.float32)
    fp = counts["FP"][0].astype(jnp.float32)
    fn = counts["FN"][0].astype(jnp.float32)
    p = tp / jnp.maximum(tp + fp, 1.0)
    r = tp / jnp.maximum(tp + fn, 1.0)
    f1 = 2 * p * r / jnp.maximum(p + r, 1e-10)
    macro = jnp.stack([p.mean(), r.mean(), f1.mean()])
    return out(BatchMetrics=macro, ClassPrecision=p, ClassRecall=r)


@register_op("auc")
def auc(attrs, ins):
    """One-shot AUC over a batch (auc_op.cc; streaming version =
    evaluator.Auc over auc_histogram)."""
    score = single(ins, "Out")
    label = single(ins, "Label").reshape(-1)
    if score.ndim == 2:
        score = score[:, -1]
    score = score.reshape(-1)
    k = int(attrs.get("num_thresholds", 200))
    bucket = jnp.clip((score * k).astype(jnp.int32), 0, k - 1)
    pos_h = jax.ops.segment_sum((label > 0).astype(jnp.float64), bucket, k)
    neg_h = jax.ops.segment_sum((label <= 0).astype(jnp.float64), bucket, k)
    tp = jnp.cumsum(pos_h[::-1])
    fp = jnp.cumsum(neg_h[::-1])
    tpr = jnp.concatenate([jnp.zeros(1), tp / jnp.maximum(tp[-1], 1)])
    fpr = jnp.concatenate([jnp.zeros(1), fp / jnp.maximum(fp[-1], 1)])
    a = jnp.sum((fpr[1:] - fpr[:-1]) * (tpr[1:] + tpr[:-1]) / 2)
    return out(AUC=a.astype(jnp.float32).reshape(()))


@register_op("positive_negative_pair")
def positive_negative_pair(attrs, ins):
    """PN-pair ranking metric (positive_negative_pair_op.cc / legacy
    pnpair evaluator): among same-query item pairs with different labels,
    count score-ordering agreement."""
    score = single(ins, "Score").reshape(-1)
    label = single(ins, "Label").reshape(-1)
    query = single(ins, "QueryID").reshape(-1)
    same_q = query[:, None] == query[None, :]
    lab_gt = label[:, None] > label[None, :]
    valid = same_q & lab_gt
    s_diff = score[:, None] - score[None, :]
    pos = jnp.sum(valid & (s_diff > 0))
    neg = jnp.sum(valid & (s_diff < 0))
    neu = jnp.sum(valid & (s_diff == 0))
    one = lambda v: v.astype(jnp.float32).reshape(1)
    return {"PositivePair": [one(pos)], "NegativePair": [one(neg)],
            "NeutralPair": [one(neu)]}


@register_op("detection_output")
def detection_output(attrs, ins):
    """Minimal SSD-style detection head (detection_output_op.cc): per class,
    keep score >= threshold, greedy IoU NMS, top_k results.
    Scores [b, n_box, n_cls]; Boxes [b, n_box, 4] (x1 y1 x2 y2)."""
    scores = single(ins, "Scores")
    boxes = single(ins, "Boxes")
    thresh = attrs.get("score_threshold", 0.01)
    nms_iou = attrs.get("nms_threshold", 0.45)
    keep_k = int(attrs.get("nms_top_k", 16))
    b, n, _ = boxes.shape

    def iou(box, others):
        x1 = jnp.maximum(box[0], others[:, 0])
        y1 = jnp.maximum(box[1], others[:, 1])
        x2 = jnp.minimum(box[2], others[:, 2])
        y2 = jnp.minimum(box[3], others[:, 3])
        inter = jnp.clip(x2 - x1, 0) * jnp.clip(y2 - y1, 0)
        a1 = (box[2] - box[0]) * (box[3] - box[1])
        a2 = (others[:, 2] - others[:, 0]) * (others[:, 3] - others[:, 1])
        return inter / jnp.maximum(a1 + a2 - inter, 1e-10)

    def nms_one(cls_scores, bx):
        order_scores, order = jax.lax.top_k(cls_scores,
                                            min(keep_k, cls_scores.shape[0]))
        obx = bx[order]
        kept = jnp.zeros(order.shape[0], bool)

        def body(i, kept):
            overlaps = iou(obx[i], obx)
            sup = kept & (overlaps > nms_iou) & \
                (jnp.arange(order.shape[0]) < i)
            ok = (order_scores[i] >= thresh) & ~jnp.any(sup)
            return kept.at[i].set(ok)

        kept = jax.lax.fori_loop(0, order.shape[0], body, kept)
        return order, order_scores, kept

    n_cls = scores.shape[-1]
    all_out = []
    for c in range(n_cls):
        order, s, kept = jax.vmap(nms_one)(scores[:, :, c], boxes)
        all_out.append((order, s, kept))
    # pack: [b, n_cls*keep_k, 6] = (class, score_or_-1, x1, y1, x2, y2)
    rows = []
    for c, (order, s, kept) in enumerate(all_out):
        sel = jnp.take_along_axis(boxes, order[..., None], axis=1)
        score_out = jnp.where(kept, s, -1.0)
        cls_col = jnp.full(score_out.shape, float(c))
        rows.append(jnp.concatenate(
            [cls_col[..., None], score_out[..., None], sel], axis=-1))
    packed = jnp.concatenate(rows, axis=1)
    # cross-class cap (reference keep_top_k): the output TRUNCATES to
    # the global top-K rows by score per image
    keep_top = int(attrs.get("keep_top_k", -1))
    if 0 < keep_top < packed.shape[1]:
        _, top_i = jax.lax.top_k(packed[:, :, 1], keep_top)
        packed = jnp.take_along_axis(packed, top_i[..., None], axis=1)
    return out(Out=packed)


# ---------------------------------------------------------------------------
# cudnn-name aliases (kernel choice is XLA's, not the program's)
# ---------------------------------------------------------------------------
for _alias, _base in [("conv2d_cudnn", "conv2d"),
                      ("conv2d_transpose_cudnn", "conv2d_transpose"),
                      ("conv3d_cudnn", "conv3d"),
                      ("conv3d_transpose_cudnn", "conv3d_transpose"),
                      ("pool2d_cudnn", "pool2d"),
                      ("pool3d_cudnn", "pool3d")]:
    register_op(_alias, get_op(_base).fn,
                optional_inputs=get_op(_base).optional_inputs)

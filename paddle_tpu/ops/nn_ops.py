"""Neural-net structural ops: conv / pool / norm / dropout.

TPU-native replacements for the reference's cuDNN-backed kernels
(/root/reference/paddle/operators/conv_op.cc, conv_cudnn_op.cu.cc,
pool_op.cc, batch_norm_op.cc, lrn_op.cc, dropout_op.cc,
operators/math/im2col.cc — im2col+gemm is never needed here: XLA lowers
lax.conv_general_dilated straight onto the MXU).

Layout: ops accept a ``data_format`` attr ("NCHW" reference default, "NHWC"
TPU-preferred). Models built for benchmarking use NHWC so the channel dim
lands on the 128-lane axis without relayout.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import register_op
from . import common
from .common import maybe, normalize_pair, out, single


def _conv_dn(fmt: str):
    if fmt == "NHWC":
        return ("NHWC", "HWIO", "NHWC")
    return ("NCHW", "OIHW", "NCHW")


@register_op("conv2d")
def conv2d(attrs, ins):
    x = single(ins, "Input")
    w = single(ins, "Filter")
    x, w = common.amp_cast(x, w)
    fmt = attrs.get("data_format", "NCHW")
    strides = normalize_pair(attrs.get("strides", [1, 1]))
    pads = normalize_pair(attrs.get("paddings", [0, 0]))
    dilations = normalize_pair(attrs.get("dilations", [1, 1]))
    groups = attrs.get("groups", 1)
    # 1x1/stride-1 convs ARE matmuls: lower them as dot_general so XLA maps
    # them straight onto the MXU and can fuse elementwise producers/consumers
    # into the dot's operand/result reads (the conv emitter cannot). These are
    # the low-arithmetic-intensity layers that bound ResNet-class training
    # (PERF.md roofline), and the dot form also gives their vjp clean
    # [BHW,Cin]x[BHW,Cout] weight-grad contractions instead of transposed
    # convs.
    k_hw = (w.shape[0], w.shape[1]) if fmt == "NHWC" else (w.shape[2],
                                                           w.shape[3])
    if (k_hw == (1, 1) and tuple(strides) == (1, 1)
            and tuple(pads) == (0, 0) and groups == 1):
        if fmt == "NHWC":
            wm = w.reshape(w.shape[2], w.shape[3])  # HWIO -> [I, O]
            B, H, W_, I = x.shape
            y = jax.lax.dot_general(
                x.reshape(B * H * W_, I), wm, (((1,), (0,)), ((), ())),
                precision=common.mxu_precision())
            return out(Output=y.reshape(B, H, W_, -1).astype(x.dtype))
        wm = w.reshape(w.shape[0], w.shape[1])  # OIHW -> [O, I]
        y = jax.lax.dot_general(
            x, wm, (((1,), (1,)), ((), ())),
            precision=common.mxu_precision())  # [B,H,W,O]
        return out(Output=jnp.moveaxis(y, -1, 1).astype(x.dtype))
    y = jax.lax.conv_general_dilated(
        x,
        w,
        window_strides=strides,
        padding=[(pads[0], pads[0]), (pads[1], pads[1])],
        rhs_dilation=dilations,
        dimension_numbers=_conv_dn(fmt),
        feature_group_count=groups,
        precision=common.mxu_precision(),
        # No preferred_element_type: the MXU accumulates bf16 products in f32
        # internally either way, and a widened output dtype breaks the
        # transpose(conv) dtype match under jax.vjp.
    )
    return out(Output=y.astype(x.dtype))


@register_op("depthwise_conv2d")
def depthwise_conv2d(attrs, ins):
    a = dict(attrs)
    x = single(ins, "Input")
    fmt = a.get("data_format", "NCHW")
    channels = x.shape[1] if fmt == "NCHW" else x.shape[-1]
    a["groups"] = channels
    return conv2d(a, ins)


@register_op("conv2d_transpose")
def conv2d_transpose(attrs, ins):
    x = single(ins, "Input")
    w = single(ins, "Filter")  # reference layout: [in_c, out_c, kh, kw]
    fmt = attrs.get("data_format", "NCHW")
    strides = normalize_pair(attrs.get("strides", [1, 1]))
    pads = normalize_pair(attrs.get("paddings", [0, 0]))
    dilations = normalize_pair(attrs.get("dilations", [1, 1]))
    if fmt == "NHWC":
        dn = ("NHWC", "HWIO", "NHWC")
        kh, kw = w.shape[0], w.shape[1]
        w_flip = w[::-1, ::-1]
    else:
        dn = ("NCHW", "IOHW", "NCHW")
        kh, kw = w.shape[2], w.shape[3]
        w_flip = w[:, :, ::-1, ::-1]
    pad_h = dilations[0] * (kh - 1) - pads[0]
    pad_w = dilations[1] * (kw - 1) - pads[1]
    # transpose conv = fractionally-strided conv with the spatially-flipped
    # kernel; the IOHW/HWIO dimension spec handles the channel swap
    # (conv_general_dilated has no transpose_kernel arg in this JAX)
    y = jax.lax.conv_general_dilated(
        x,
        w_flip,
        window_strides=(1, 1),
        padding=[(pad_h, pad_h), (pad_w, pad_w)],
        lhs_dilation=strides,
        rhs_dilation=dilations,
        dimension_numbers=dn,
    )
    return out(Output=y)


@register_op("pool2d")
def pool2d(attrs, ins):
    x = single(ins, "X")
    fmt = attrs.get("data_format", "NCHW")
    ptype = attrs.get("pooling_type", "max")
    ksize = normalize_pair(attrs.get("ksize", [2, 2]))
    strides = normalize_pair(attrs.get("strides", [1, 1]))
    pads = normalize_pair(attrs.get("paddings", [0, 0]))
    if fmt == "NHWC":
        window = (1, ksize[0], ksize[1], 1)
        stride = (1, strides[0], strides[1], 1)
        padding = [(0, 0), (pads[0], pads[0]), (pads[1], pads[1]), (0, 0)]
        spatial = (1, 2)
    else:
        window = (1, 1, ksize[0], ksize[1])
        stride = (1, 1, strides[0], strides[1])
        padding = [(0, 0), (0, 0), (pads[0], pads[0]), (pads[1], pads[1])]
        spatial = (2, 3)
    if attrs.get("global_pooling", False):
        window = tuple(x.shape[i] if i in spatial else 1 for i in range(x.ndim))
        stride = (1,) * x.ndim
        padding = [(0, 0)] * x.ndim
    elif attrs.get("ceil_mode", False):
        # legacy v1 semantics (config_parser.py cnn_output_size with
        # caffe_mode=False): output = ceil((I + 2p - F)/S) + 1 — realised
        # as extra high-side padding. The exclusive-average count below
        # already ignores the synthetic cells.
        for d in spatial:
            i_dim, f, s = x.shape[d], window[d], stride[d]
            lo, hi = padding[d]
            out_dim = -(-(i_dim + lo + hi - f) // s) + 1
            # legacy clamp: the last window must start inside the (user-
            # padded) input, else it would pool only synthetic cells
            # (NaN for exclusive-avg, -inf for max)
            if (out_dim - 1) * s >= i_dim + lo:
                out_dim -= 1
            need = (out_dim - 1) * s + f - (i_dim + lo + hi)
            padding[d] = (lo, hi + max(0, need))
    # init values must be Python scalars so JAX recognises the monoid and
    # uses the differentiable reduce_window_{sum,max} primitives
    if ptype == "max":
        init = -np.inf if jnp.issubdtype(x.dtype, jnp.floating) else np.iinfo(x.dtype).min
        y = jax.lax.reduce_window(x, init, jax.lax.max,
                                  window, stride, padding)
    else:
        s = jax.lax.reduce_window(x, 0.0, jax.lax.add,
                                  window, stride, padding)
        if attrs.get("exclusive", True) and any(p != (0, 0) for p in padding):
            ones = jnp.ones_like(x)
            cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add,
                                        window, stride, padding)
            y = s / cnt
        else:
            y = s / np.prod([w for w in window])
    return out(Out=y.astype(x.dtype))


def _bn_axes(fmt, ndim):
    """(reduce axes, per-channel broadcast shape) for a BN input layout."""
    if fmt == "NCHW" and ndim == 4:
        return (0, 2, 3), (1, -1, 1, 1)
    if ndim == 4:  # NHWC
        return (0, 1, 2), (1, 1, 1, -1)
    return (0,), (1, -1)  # 2-D [N, C]


def _batch_norm_grad(attrs, ins, outs, ogs):
    """Hand-written BN backward (the reference's batch_norm_grad kernel
    formulas). The generic vjp-of-forward grad would recompute the f32-cast
    activation and the (x - mean) products; XLA CSEs those with the forward
    and the f32 copies then live in HBM from forward to backward — measured
    as the dominant convert/normalize byte stream of ResNet-class training
    (PERF.md). Here the only tensor residuals are the bf16 activation the
    forward already keeps and the tiny per-channel stats: x-hat is
    rebuilt in-register from them (in the distributed ``x*inv - mean*inv``
    form, structurally different from the forward's ``(x-mean)*k`` so CSE
    cannot pin a shared f32 intermediate), and every reduction accumulates
    in f32 off bf16 reads."""
    x = single(ins, "X")
    scale = single(ins, "Scale")
    # Every stat output is a plain differentiable function of the inputs:
    # mean_out/var_out = momentum*old + (1-momentum)*batch_stat (train)
    # or identity aliases of the running stats (test); saved_mean is the
    # batch mean and saved_variance the batch inverse std (train) or the
    # same aliases (test). All cotangents flow below.
    gm = ogs.get("MeanOut", [None])[0]
    gv = ogs.get("VarianceOut", [None])[0]
    gsm = ogs.get("SavedMean", [None])[0]
    gsv = ogs.get("SavedVariance", [None])[0]
    dy = ogs.get("Y", [None])[0]
    if all(g is None for g in (dy, gm, gv, gsm, gsv)):
        raise NotImplementedError("batch_norm grad with no output grads")
    momentum = attrs.get("momentum", 0.9)
    fmt = attrs.get("data_layout", attrs.get("data_format", "NCHW"))
    axes, bshape = _bn_axes(fmt, x.ndim)
    eps = attrs.get("epsilon", 1e-5)
    # Saved stats when the layer wired those outputs; otherwise recompute
    # with the forward's exact expressions so XLA CSEs them (the stats are
    # [C]-sized — keeping them is free, recomputing them is one fused pass).
    sm = outs.get("SavedMean", [None])[0]
    sv = outs.get("SavedVariance", [None])[0]
    if attrs.get("is_test", False):
        mean = single(ins, "Mean").astype(jnp.float32)
        inv = jax.lax.rsqrt(
            single(ins, "Variance").astype(jnp.float32) + eps)
    elif sm is not None and sv is not None:
        mean = sm.astype(jnp.float32)
        inv = sv.astype(jnp.float32)  # fwd saves 1/sqrt(var+eps)
    else:
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=axes)
        bvar = jnp.mean(jnp.square(xf), axis=axes) - jnp.square(mean)
        inv = jax.lax.rsqrt(bvar + eps)
    xhat = (x.astype(jnp.float32) * inv.reshape(bshape)
            - (mean * inv).reshape(bshape))
    if dy is not None:
        dyf = dy.astype(jnp.float32)
        dbias = jnp.sum(dyf, axis=axes)
        dscale = jnp.sum(dyf * xhat, axis=axes)
    else:
        dyf = None
        dbias = dscale = jnp.zeros(scale.shape, jnp.float32)
    k = (scale.astype(jnp.float32) * inv).reshape(bshape)
    grads = {"Scale": [dscale.astype(scale.dtype)],
             "Bias": [dbias.astype(single(ins, "Bias").dtype)]}
    sc = scale.astype(jnp.float32)
    dmean_in = dvar_in = None
    if attrs.get("is_test", False):
        # running stats are INPUTS here, and Y genuinely depends on them:
        # dY/dMean = -scale*inv, dY/dVar = -(x-mean)*scale*inv^3/2;
        # MeanOut/VarianceOut alias the inputs; SavedMean = Mean and
        # SavedVariance = rsqrt(Variance+eps) are functions of them too
        dx = dyf * k if dyf is not None else jnp.zeros_like(x)
        dmean_in = -sc * inv * dbias
        dvar_in = -0.5 * sc * jnp.square(inv) * dscale
        if gm is not None:
            dmean_in = dmean_in + gm.astype(jnp.float32)
        if gv is not None:
            dvar_in = dvar_in + gv.astype(jnp.float32)
        if gsm is not None:
            dmean_in = dmean_in + gsm.astype(jnp.float32)
        if gsv is not None:
            dvar_in = dvar_in - 0.5 * (inv ** 3) \
                * gsv.astype(jnp.float32)
    else:
        n = x.size // scale.size
        if dyf is not None:
            dx = k * (dyf - (dbias.reshape(bshape)
                             + xhat * dscale.reshape(bshape)) / n)
        else:
            dx = jnp.zeros(x.shape, jnp.float32)
        # mean_out/var_out = momentum*old + (1-momentum)*batch_stat:
        # batch_mean -> x jacobian is 1/n; batch_var -> x is 2(x-mean)/n;
        # saved_mean = batch_mean, saved_variance = rsqrt(batch_var+eps)
        if gm is not None:
            gmf = gm.astype(jnp.float32)
            dx = dx + ((1.0 - momentum) / n) * gmf.reshape(bshape)
            dmean_in = momentum * gmf
        if gv is not None:
            gvf = gv.astype(jnp.float32)
            dx = dx + ((1.0 - momentum) * 2.0 / n) * gvf.reshape(bshape) \
                * (xhat / inv.reshape(bshape))
            dvar_in = momentum * gvf
        if gsm is not None:
            dx = dx + gsm.astype(jnp.float32).reshape(bshape) / n
        if gsv is not None:
            dx = dx - ((inv ** 3).reshape(bshape) / n) \
                * gsv.astype(jnp.float32).reshape(bshape) \
                * (xhat / inv.reshape(bshape))
    if dmean_in is not None:
        grads["Mean"] = [dmean_in.astype(single(ins, "Mean").dtype)]
    if dvar_in is not None:
        grads["Variance"] = [dvar_in
                             .astype(single(ins, "Variance").dtype)]
    grads["X"] = [dx.astype(x.dtype)]
    return grads


@register_op("batch_norm", grad_fn=_batch_norm_grad,
             grad_fn_is_optimization=True)
def batch_norm(attrs, ins):
    """Reference batch_norm_op.cc semantics.

    Training: normalise with batch stats, update running Mean/Variance with
    ``momentum``. The layer aliases MeanOut/VarianceOut onto Mean/Variance so
    the functional state-threading performs the reference's in-place update.
    """
    x = single(ins, "X")
    scale = single(ins, "Scale")
    bias = single(ins, "Bias")
    mean = single(ins, "Mean")
    var = single(ins, "Variance")
    eps = attrs.get("epsilon", 1e-5)
    momentum = attrs.get("momentum", 0.9)
    fmt = attrs.get("data_layout", attrs.get("data_format", "NCHW"))
    is_test = attrs.get("is_test", False)

    axes, bshape = _bn_axes(fmt, x.ndim)

    xf = x.astype(jnp.float32)
    if is_test:
        use_mean, use_var = mean, var
        mean_out, var_out = mean, var
        saved_mean = mean
        saved_inv_std = jax.lax.rsqrt(var + eps)
    else:
        bmean = jnp.mean(xf, axis=axes)
        bvar = jnp.mean(jnp.square(xf), axis=axes) - jnp.square(bmean)
        use_mean, use_var = bmean, bvar
        mean_out = momentum * mean + (1 - momentum) * bmean
        var_out = momentum * var + (1 - momentum) * bvar
        saved_mean = bmean
        saved_inv_std = jax.lax.rsqrt(bvar + eps)
    inv = jax.lax.rsqrt(use_var + eps)
    y = (xf - use_mean.reshape(bshape)) * (inv * scale).reshape(bshape) + bias.reshape(bshape)
    return {
        "Y": [y.astype(x.dtype)],
        "MeanOut": [mean_out],
        "VarianceOut": [var_out],
        "SavedMean": [saved_mean],
        "SavedVariance": [saved_inv_std],
    }


def _layer_norm_grad(attrs, ins, outs, ogs):
    """Hand-written LN backward — same byte motive as ``_batch_norm_grad``:
    the transformer path pays two LNs per block, and the generic vjp keeps
    an f32 cast + x-hat of every [b, T, d] activation alive across
    forward->backward. Residuals here are the bf16 x plus the per-position
    Mean/Variance rows the forward already emits."""
    x = single(ins, "X")
    scale = maybe(ins, "Scale")
    bias = maybe(ins, "Bias")
    gmean = ogs.get("Mean", [None])[0]
    gvar = ogs.get("Variance", [None])[0]
    dy = ogs.get("Y", [None])[0]
    if dy is None and gmean is None and gvar is None:
        raise NotImplementedError("layer_norm grad with no output grads")
    eps = attrs.get("epsilon", 1e-5)
    begin = attrs.get("begin_norm_axis", 1)
    axes = tuple(range(begin, x.ndim))
    kshape = x.shape[:begin] + (1,) * (x.ndim - begin)
    m = outs.get("Mean", [None])[0]
    v = outs.get("Variance", [None])[0]
    if m is not None and v is not None:
        meanb = m.astype(jnp.float32).reshape(kshape)
        varb = v.astype(jnp.float32).reshape(kshape)
    else:
        # recompute with the forward's exact expressions -> CSE'd by XLA;
        # the per-position rows are tiny next to the activation itself
        xf = x.astype(jnp.float32)
        meanb = jnp.mean(xf, axis=axes, keepdims=True)
        varb = jnp.mean(jnp.square(xf - meanb), axis=axes, keepdims=True)
    inv = jax.lax.rsqrt(varb + eps)
    xhat = x.astype(jnp.float32) * inv - meanb * inv
    norm_shape = x.shape[begin:]
    nn = int(np.prod(norm_shape))
    grads = {}
    if dy is not None:
        dyf = dy.astype(jnp.float32)
        if scale is not None:
            dxhat = dyf * scale.astype(jnp.float32).reshape(
                (1,) * begin + norm_shape)
        else:
            dxhat = dyf
        m1 = jnp.mean(dxhat, axis=axes, keepdims=True)
        m2 = jnp.mean(dxhat * xhat, axis=axes, keepdims=True)
        dx = inv * (dxhat - m1 - xhat * m2)
        batch_axes = tuple(range(begin))
        if scale is not None:
            grads["Scale"] = [jnp.sum(dyf * xhat, axis=batch_axes)
                              .reshape(scale.shape).astype(scale.dtype)]
        if bias is not None:
            grads["Bias"] = [jnp.sum(dyf, axis=batch_axes)
                             .reshape(bias.shape).astype(bias.dtype)]
    else:
        dx = jnp.zeros(x.shape, jnp.float32)
    # Mean/Variance OUTPUTS are plain differentiable functions of X:
    # d mean/dx = 1/n, d var/dx = 2(x-mean)/n (the dm/dx terms cancel).
    if gmean is not None:
        dx = dx + gmean.astype(jnp.float32).reshape(kshape) / nn
    if gvar is not None:
        dx = dx + gvar.astype(jnp.float32).reshape(kshape) \
            * (2.0 / nn) * (xhat / inv)
    grads["X"] = [dx.astype(x.dtype)]
    return grads


@register_op("layer_norm", grad_fn=_layer_norm_grad,
             grad_fn_is_optimization=True)
def layer_norm(attrs, ins):
    x = single(ins, "X")
    eps = attrs.get("epsilon", 1e-5)
    begin = attrs.get("begin_norm_axis", 1)
    axes = tuple(range(begin, x.ndim))
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=axes, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    y = (xf - mean) * inv
    scale = maybe(ins, "Scale")
    bias = maybe(ins, "Bias")
    norm_shape = x.shape[begin:]
    if scale is not None:
        y = y * scale.reshape((1,) * begin + norm_shape)
    if bias is not None:
        y = y + bias.reshape((1,) * begin + norm_shape)
    return {
        "Y": [y.astype(x.dtype)],
        "Mean": [mean.reshape(x.shape[:begin])],
        "Variance": [var.reshape(x.shape[:begin])],
    }


def _rms_norm_grad(attrs, ins, outs, ogs):
    """Hand-written RMSNorm backward (same byte policy as the BN/LN
    grads: bf16 residuals only, f32 reduction accumulation, x-hat
    rebuilt in-register). dx = inv*(dxhat - xhat*mean(dxhat*xhat))."""
    x = single(ins, "X")
    scale = maybe(ins, "Scale")
    bias = maybe(ins, "Bias")
    ginv = ogs.get("InvRms", [None])[0]
    dy = ogs.get("Y", [None])[0]
    if dy is None and ginv is None:
        raise NotImplementedError("rms_norm grad with no output grads")
    eps = attrs.get("epsilon", 1e-6)
    begin = attrs.get("begin_norm_axis", 1)
    axes = tuple(range(begin, x.ndim))
    kshape = x.shape[:begin] + (1,) * (x.ndim - begin)
    iv = outs.get("InvRms", [None])[0]
    if iv is not None:
        inv = iv.astype(jnp.float32).reshape(kshape)
    else:
        xf = x.astype(jnp.float32)
        inv = jax.lax.rsqrt(
            jnp.mean(jnp.square(xf), axis=axes, keepdims=True) + eps)
    xhat = x.astype(jnp.float32) * inv
    norm_shape = x.shape[begin:]
    grads = {}
    if dy is not None:
        dyf = dy.astype(jnp.float32)
        if scale is not None:
            dxhat = dyf * scale.astype(jnp.float32).reshape(
                (1,) * begin + norm_shape)
        else:
            dxhat = dyf
        m = jnp.mean(dxhat * xhat, axis=axes, keepdims=True)
        dx = inv * (dxhat - xhat * m)
        batch_axes = tuple(range(begin))
        if scale is not None:
            grads["Scale"] = [jnp.sum(dyf * xhat, axis=batch_axes)
                              .reshape(scale.shape).astype(scale.dtype)]
        if bias is not None:
            grads["Bias"] = [jnp.sum(dyf, axis=batch_axes)
                             .reshape(bias.shape).astype(bias.dtype)]
    else:
        dx = jnp.zeros(x.shape, jnp.float32)
    # InvRms is differentiable too: d inv/dx = -inv^3 * x / n
    if ginv is not None:
        nn = int(np.prod(norm_shape))
        dx = dx + ginv.astype(jnp.float32).reshape(kshape) \
            * (-(inv ** 3)) * x.astype(jnp.float32) / nn
    grads["X"] = [dx.astype(x.dtype)]
    return grads


@register_op("rms_norm", grad_fn=_rms_norm_grad,
             grad_fn_is_optimization=True,
             optional_inputs=("Scale", "Bias"))
def rms_norm(attrs, ins):
    """Root-mean-square normalization (beyond-reference: the reference
    predates RMSNorm; modern LM stacks default to it). TPU-friendlier
    than layer_norm — ONE reduction per row, no mean subtraction:
    y = x * rsqrt(mean(x^2) + eps) * scale (+ bias)."""
    x = single(ins, "X")
    eps = attrs.get("epsilon", 1e-6)
    begin = attrs.get("begin_norm_axis", 1)
    axes = tuple(range(begin, x.ndim))
    xf = x.astype(jnp.float32)
    inv = jax.lax.rsqrt(
        jnp.mean(jnp.square(xf), axis=axes, keepdims=True) + eps)
    y = xf * inv
    scale = maybe(ins, "Scale")
    bias = maybe(ins, "Bias")
    norm_shape = x.shape[begin:]
    if scale is not None:
        y = y * scale.reshape((1,) * begin + norm_shape)
    if bias is not None:
        y = y + bias.reshape((1,) * begin + norm_shape)
    return {"Y": [y.astype(x.dtype)],
            "InvRms": [inv.reshape(x.shape[:begin])]}


@register_op("lrn")
def lrn(attrs, ins):
    """Local response normalisation across channels (lrn_op.cc); the
    data_format attr extends the reference's NCHW-only kernel to NHWC."""
    x = single(ins, "X")
    n = attrs.get("n", 5)
    k = attrs.get("k", 2.0)
    alpha = attrs.get("alpha", 1e-4)
    beta = attrs.get("beta", 0.75)
    ch_axis = 3 if attrs.get("data_format", "NCHW") == "NHWC" else 1
    nch = x.shape[ch_axis]
    sq = jnp.square(x)
    half = n // 2
    pad_widths = [(0, 0)] * x.ndim
    pad_widths[ch_axis] = (half, half)
    pad = jnp.pad(sq, pad_widths)
    acc = sum(jax.lax.slice_in_dim(pad, i, i + nch, axis=ch_axis)
              for i in range(n))
    mid = k + alpha * acc
    return {"Out": [x / jnp.power(mid, beta)], "MidOut": [mid]}


def _dropout_grad(attrs, ins, outs, ogs):
    mask = outs["Mask"][0]
    og = ogs["Out"][0]
    return {"X": [og * mask.astype(og.dtype)]}


@register_op("dropout", needs_rng=True, grad_fn=_dropout_grad)
def dropout(attrs, ins, rng):
    x = single(ins, "X")
    p = attrs.get("dropout_prob", 0.5)
    if attrs.get("is_test", False):
        # Reference (downscale-in-infer mode) scales at inference.
        return {"Out": [x * (1.0 - p)], "Mask": [jnp.ones_like(x)]}
    keep = jax.random.bernoulli(rng, 1.0 - p, x.shape)
    return {"Out": [jnp.where(keep, x, 0.0)], "Mask": [keep.astype(x.dtype)]}


@register_op("im2sequence")
def im2sequence(attrs, ins):
    """Extract conv-style patches into a [N*outH*outW, C*kh*kw] matrix
    (im2sequence_op.cc / legacy BlockExpandLayer)."""
    x = single(ins, "X")  # NCHW
    kh, kw = normalize_pair(attrs["kernels"])
    sh, sw = normalize_pair(attrs.get("strides", [1, 1]))
    p = attrs.get("paddings", [0, 0, 0, 0])
    xp = jnp.pad(x, [(0, 0), (0, 0), (p[0], p[2] if len(p) > 2 else p[0]),
                     (p[1] if len(p) > 1 else p[0], p[3] if len(p) > 3 else p[1])])
    n, c, h, w = xp.shape
    oh = (h - kh) // sh + 1
    ow = (w - kw) // sw + 1
    patches = jax.lax.conv_general_dilated_patches(
        xp, (kh, kw), (sh, sw), padding="VALID",
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    # patches: [N, C*kh*kw, oh, ow] -> [N*oh*ow, C*kh*kw]
    seq = jnp.transpose(patches, (0, 2, 3, 1)).reshape(n * oh * ow, c * kh * kw)
    return out(Out=seq)

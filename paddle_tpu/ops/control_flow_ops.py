"""Control-flow ops: static_rnn (scan), while (while_loop), tensor arrays,
and a fused beam-search decoder.

TPU-native replacement for the reference's control-flow machinery:
- recurrent_op.cc:222 (StepScopes per-timestep sub-scope execution)
- while_op.cc (sub-block interpreted until a cond var flips)
- lod_tensor_to_array / array ops (LoDTensorArray plumbing for dynamic RNN)
- beam_search_op.cc + beam_search_decode_op.cc, and the legacy
  RecurrentGradientMachine::generateSequence/beamSearch
  (gserver/gradientmachines/RecurrentGradientMachine.h:307-309)

The reference executes sub-blocks with a per-op interpreter inside step
scopes. Here a sub-block is *data*: the layer builders (layers/control_flow.py)
serialize the body's ops (type/inputs/outputs/attrs — all plain values) into
the parent op's attrs, and the kernel re-materialises the body as a pure JAX
function evaluated under ``lax.scan`` / ``lax.while_loop``. That keeps these
ops ordinary pure kernels — so ``static_rnn`` is reverse-differentiable
through ``lax.scan`` and the generic vjp backward works unchanged, with no
executor special-casing and no StepScope state.

Body-op environment contract (shared by static_rnn/while):
  x_names     — per-step values (sliced from time axis / loop-carried)
  mem_names   — loop-carried state, seeded from MemInit
  param_names — external reads (weights etc.), constant across steps
"""
from __future__ import annotations

from typing import Dict, List

import jax
import jax.numpy as jnp

from ..core.registry import get_op, register_op
from .common import maybe, out
from .sequence_ops import time_mask


def run_body(body_ops: List[dict], env: Dict[str, jax.Array]) -> Dict:
    """Execute serialized body ops over an env dict (pure; traceable)."""
    for od in body_ops:
        opdef = get_op(od["type"])
        if opdef.needs_rng or opdef.special:
            raise NotImplementedError(
                f"op {od['type']!r} cannot run inside a control-flow body")
        ins = {slot: [env[n] for n in names]
               for slot, names in od["inputs"].items() if names}
        outs = opdef.fn(od["attrs"], ins)
        for slot, names in od["outputs"].items():
            vals = outs.get(slot, [])
            for n, v in zip(names, vals):
                env[n] = v
    return env


@register_op("static_rnn",
             optional_inputs=("X", "MemInit", "Param", "Length"))
def static_rnn(attrs, ins):
    """User-defined recurrence over the time axis (recurrent_op.cc:222).

    Sequence inputs [b, T, ...] are sliced per step; memories carry across
    steps; per-step outputs are re-stacked to [b, T, ...]. With Length,
    finished rows freeze their memories and zero their outputs (LoD
    semantics, same masking as the lstm/gru kernels).
    """
    xs = ins.get("X", [])
    mem_init = ins.get("MemInit", [])
    params = ins.get("Param", [])
    lengths = maybe(ins, "Length")
    body_ops = attrs["body_ops"]
    x_names = attrs["x_names"]
    mem_names = attrs["mem_names"]
    mem_out_names = attrs["mem_out_names"]
    out_names = attrs["out_names"]
    param_names = attrs["param_names"]

    T = xs[0].shape[1] if xs else attrs["seq_len_static"]
    base_env = dict(zip(param_names, params))
    xs_tm = [jnp.swapaxes(x, 0, 1) for x in xs]  # time-major
    mask_tm = (jnp.swapaxes(time_mask(lengths, T, mem_init[0].dtype
                                      if mem_init else jnp.float32), 0, 1)
               if lengths is not None else None)

    def step(carry, slices):
        if mask_tm is not None:
            xt, m = slices
        else:
            xt, m = (slices if slices is not None else ()), None
        env = dict(base_env)
        env.update(zip(x_names, xt))
        env.update(zip(mem_names, carry))
        env = run_body(body_ops, env)
        new_carry = []
        for old, name in zip(carry, mem_out_names):
            new = env[name]
            if m is not None:
                mm = m.reshape(m.shape + (1,) * (new.ndim - 1))
                new = mm * new + (1 - mm) * old
            new_carry.append(new)
        step_outs = []
        for name in out_names:
            y = env[name]
            if m is not None:
                mm = m.reshape(m.shape + (1,) * (y.ndim - 1))
                y = y * mm.astype(y.dtype)
            step_outs.append(y)
        return tuple(new_carry), tuple(step_outs)

    if mask_tm is None:
        seq = tuple(xs_tm) if xs_tm else None
        carry, ys = jax.lax.scan(step, tuple(mem_init), seq,
                                 length=None if xs_tm else T)
    else:
        carry, ys = jax.lax.scan(step, tuple(mem_init),
                                 (tuple(xs_tm), mask_tm))
    outputs = [jnp.swapaxes(y, 0, 1) for y in ys]
    return {"Out": outputs, "LastMem": list(carry)}


@register_op("while", optional_inputs=("Param",))
def while_op(attrs, ins):
    """Functional while (while_op.cc): body runs until the carried cond var
    is false. Carried vars are the loop state; the body must reassign each
    (typically via ``assign``/arithmetic writing the same name).

    Two lowerings:
    - ``max_iters`` set -> a fixed-trip ``lax.scan`` where steps whose cond
      has gone false pass the carry through unchanged. This is
      reverse-differentiable, so while-graphs TRAIN — the TPU answer to the
      reference differentiating while sub-blocks
      (/root/reference/paddle/framework/backward.cc:415 MakeBlockBackward).
      The trip count is static (compiler-friendly); inactive tail steps are
      masked no-ops.
    - otherwise -> ``lax.while_loop`` with true early exit (decode-side
      loops: beam search, generation). Not reverse-differentiable; pass
      max_iters if the loop must be trained through.
    """
    carried_in = ins["Carried"]
    params = ins.get("Param", [])
    body_ops = attrs["body_ops"]
    carried_names = attrs["carried_names"]
    param_names = attrs["param_names"]
    cond_name = attrs["cond_name"]
    max_iters = attrs.get("max_iters")
    base_env = dict(zip(param_names, params))
    cond_pos = carried_names.index(cond_name)

    def body_fn(carry):
        env = dict(base_env)
        env.update(zip(carried_names, carry))
        env = run_body(body_ops, env)
        return tuple(env[n] for n in carried_names)

    if max_iters is not None:
        def step(carry, _):
            active = jnp.reshape(carry[cond_pos], ()).astype(bool)
            new = body_fn(carry)
            merged = tuple(
                jnp.where(active, n, o) for n, o in zip(new, carry))
            return merged, None

        final, _ = jax.lax.scan(step, tuple(carried_in), None,
                                length=int(max_iters))
        return {"Out": list(final)}

    def cond_fn(carry):
        return jnp.reshape(carry[cond_pos], ())

    final = jax.lax.while_loop(cond_fn, body_fn, tuple(carried_in))
    return {"Out": list(final)}


@register_op("array_write")
def array_write(attrs, ins):
    """Write X into Array (a [max_len, ...] buffer) at scalar Index
    (functional LoDTensorArray write, tensor_array_read_write ops)."""
    x = ins["X"][0]
    i = jnp.reshape(ins["I"][0], ()).astype(jnp.int32)
    arr = ins["Array"][0]
    return out(Out=jax.lax.dynamic_update_index_in_dim(arr, x, i, axis=0))


@register_op("array_read")
def array_read(attrs, ins):
    i = jnp.reshape(ins["I"][0], ()).astype(jnp.int32)
    arr = ins["Array"][0]
    return out(Out=jax.lax.dynamic_index_in_dim(arr, i, axis=0,
                                                keepdims=False))


@register_op("beam_search_decoder",
             optional_inputs=("InitCell", "Bias", "OutBias"))
def beam_search_decoder(attrs, ins):
    """Fused in-graph beam-search generation with a GRU or LSTM cell.

    The TPU-native fusion of the reference's decode loop — while_op +
    beam_search_op (top-k prune) + beam_search_decode_op (backtrack), and the
    legacy RecurrentGradientMachine::beamSearch — into one op: a
    lax.while_loop over at most max_len steps with the whole beam resident
    on-chip; each step is one [b*beam, h] x [h, gates] MXU matmul + top-k.
    Early exit when every beam has emitted EOS (the reference's
    eos-pruning, RecurrentGradientMachine.cpp:98-117).

    Inputs:
      InitState [b, h]   — decoder initial hidden state
      InitCell  [b, h]   — (LSTM only) initial cell state
      Embedding [V, e]   — target-side embedding table
      WeightX   [e, G*h] — input->gates projection (G=3 GRU, G=4 LSTM)
      WeightH   [h, G*h] — hidden->gates recurrence
      Bias      [1, G*h]
      WeightOut [h, V], OutBias [V] — output projection to vocab logits

    Outputs: Ids [b, beam, max_len] int32 (post-BOS tokens, padded with
    eos_id), SeqScores [b, beam] total log-prob (best first), SeqLen
    [b, beam] int32 generated lengths (excluding EOS).
    """
    init_h = ins["InitState"][0]
    init_c = maybe(ins, "InitCell")
    emb = ins["Embedding"][0]
    wx = ins["WeightX"][0]
    wh = ins["WeightH"][0]
    bias = maybe(ins, "Bias")
    w_out = ins["WeightOut"][0]
    b_out = maybe(ins, "OutBias")

    beam = int(attrs.get("beam_size", 4))
    max_len = int(attrs.get("max_len", 32))
    bos = int(attrs.get("bos_id", 0))
    eos = int(attrs.get("eos_id", 1))
    cell_kind = attrs.get("cell", "gru")
    b, h = init_h.shape
    V = emb.shape[0]
    neg_inf = jnp.asarray(jnp.finfo(jnp.float32).min, jnp.float32)

    def cell_step(tok, hc):
        """One decoder cell step over flattened [b*beam] rows."""
        x = emb[tok]  # [N, e]
        hs, cs = hc
        gates_x = jnp.dot(x, wx)
        if bias is not None:
            gates_x = gates_x + bias
        if cell_kind == "gru":
            gx, cx = gates_x[..., : 2 * h], gates_x[..., 2 * h:]
            g = jax.nn.sigmoid(gx + jnp.dot(hs, wh[:, : 2 * h]))
            u, r = g[..., :h], g[..., h:]
            cand = jnp.tanh(cx + jnp.dot(r * hs, wh[:, 2 * h:]))
            new_h = (1.0 - u) * hs + u * cand
            return new_h, (new_h, cs)
        gates = gates_x + jnp.dot(hs, wh)
        gc, gi, gf, go = jnp.split(gates, 4, axis=-1)
        c_new = jax.nn.sigmoid(gf) * cs + jax.nn.sigmoid(gi) * jnp.tanh(gc)
        new_h = jax.nn.sigmoid(go) * jnp.tanh(c_new)
        return new_h, (new_h, c_new)

    # State over [b, beam] lattices.
    hs0 = jnp.broadcast_to(init_h[:, None], (b, beam, h))
    cs0 = (jnp.broadcast_to(init_c[:, None], (b, beam, h))
           if init_c is not None else jnp.zeros_like(hs0))
    # Only beam 0 is live at t=0 (all beams start identical).
    scores0 = jnp.where(jnp.arange(beam)[None, :] == 0, 0.0, neg_inf)
    scores0 = jnp.broadcast_to(scores0, (b, beam)).astype(jnp.float32)
    state0 = (
        jnp.zeros((b, beam), jnp.bool_),             # finished
        scores0,                                     # cumulative log-prob
        jnp.full((b, beam), bos, jnp.int32),         # last token
        (hs0, cs0),                                  # cell state
        jnp.full((b, beam, max_len), eos, jnp.int32),  # emitted ids
        jnp.zeros((b, beam), jnp.int32),             # lengths
        jnp.asarray(0, jnp.int32),                   # t
    )

    def cond(state):
        finished, _, _, _, _, _, t = state
        return jnp.logical_and(t < max_len, ~jnp.all(finished))

    def step(state):
        finished, scores, last, (hs, cs), ids, lens, t = state
        flat = lambda a: a.reshape((b * beam,) + a.shape[2:])
        logit_h, (new_h, new_c) = cell_step(flat(last), (flat(hs), flat(cs)))
        logits = jnp.dot(logit_h, w_out)
        if b_out is not None:
            logits = logits + b_out
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        logp = logp.reshape(b, beam, V)
        # Finished beams may only "emit" EOS at zero cost — keeps exactly one
        # live continuation per finished beam (beam_search_op.cc prune).
        eos_only = jnp.full((V,), neg_inf).at[eos].set(0.0)
        logp = jnp.where(finished[..., None], eos_only[None, None, :], logp)
        cand = scores[..., None] + logp  # [b, beam, V]
        top_scores, top_idx = jax.lax.top_k(cand.reshape(b, beam * V), beam)
        src_beam = top_idx // V  # [b, beam]
        tok = (top_idx % V).astype(jnp.int32)

        take = lambda a: jnp.take_along_axis(
            a, src_beam.reshape((b, beam) + (1,) * (a.ndim - 2)), axis=1)
        new_h = take(new_h.reshape(b, beam, h))
        new_c = take(new_c.reshape(b, beam, h))
        ids = take(ids)
        lens = jnp.take_along_axis(lens, src_beam, axis=1)
        was_fin = jnp.take_along_axis(finished, src_beam, axis=1)
        ids = jnp.where((jnp.arange(max_len) == t)[None, None, :]
                        & ~was_fin[..., None], tok[..., None], ids)
        now_fin = was_fin | (tok == eos)
        lens = jnp.where(~was_fin & (tok != eos), lens + 1, lens)
        return (now_fin, top_scores, tok, (new_h, new_c), ids, lens, t + 1)

    finished, scores, _, _, ids, lens, _ = jax.lax.while_loop(
        cond, step, state0)
    return out(Ids=ids, SeqScores=scores, SeqLen=lens)


@register_op("cond", optional_inputs=("Param",))
def cond_op(attrs, ins):
    """Functional two-branch conditional (cond_op.cc / if_else design doc):
    scalar Cond picks which serialized branch runs under lax.cond. Both
    branches must write the same output names (attrs out_names); inputs are
    the union of branch reads (Param slot)."""
    pred = jnp.reshape(ins["Cond"][0], ()).astype(bool)
    params = ins.get("Param", [])
    param_names = attrs["param_names"]
    out_names = attrs["out_names"]
    base_env = dict(zip(param_names, params))

    def branch(body_ops):
        def fn(env):
            env = dict(env)
            env = run_body(body_ops, env)
            return tuple(env[n] for n in out_names)
        return fn

    outs = jax.lax.cond(pred, branch(attrs["true_ops"]),
                        branch(attrs["false_ops"]), base_env)
    return {"Out": list(outs)}

"""Row-granular sparse optimizer updates as first-class ops.

The scatter-apply half of the streaming CTR plane
(:mod:`paddle_tpu.online`): ``sparse_sgd`` / ``sparse_adagrad`` consume a
SelectedRows gradient and touch ONLY the looked-up rows — unique ids via
the segment-sum dedup (SelectedRows.merged), then one scatter per state
tensor. A [V, D] gradient never materializes (the reference's
sgd_op.cc / adagrad_op.cc SelectedRows kernels, originally applied on
the sparse parameter server, /root/reference/go/pserver/optimizer.go).

Touched rows follow the dense formula BITWISE (pinned by
tests/test_online.py): dedup first, then the same f32 arithmetic the
dense kernel runs per element, so sparse-vs-dense differ only in which
rows get written.

Mesh-aware: when the executor's mesh carries the plan's vocab axis (attr
``vocab_axis``, default 'mp') and the table divides, the scatters lower
through :mod:`paddle_tpu.parallel.sharded_embedding`'s shard_map islands
— each device applies the rows of ITS [V/n, D] block, the row exchange
riding the same ICI collectives as the forward gather. Otherwise (single
device, dp-only mesh, or a densified fan-in gradient) the serial path
runs; both paths share the formulas above.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.registry import register_op
from ..core.selected_rows import SelectedRows
from .common import out, single


def _vocab_mesh(attrs, vocab: int):
    """The executor mesh when this op instance should scatter through
    the shard_map island (vocab axis present, size > 1, table divides);
    None selects the serial path — the SAME program runs on one
    device (and under abstract shape inference, where no mesh is
    published)."""
    from ..parallel.context import current_mesh
    from ..parallel.sharded_embedding import rows_per_shard

    mesh = current_mesh()
    if mesh is None:
        return None
    axis = attrs.get("vocab_axis", "mp")
    if not rows_per_shard(vocab, mesh, axis):
        return None
    return mesh


@register_op("sparse_sgd")
def sparse_sgd(attrs, ins):
    """SGD over a SelectedRows gradient: dedup the touched rows, then
    ``param[rows] -= lr * grad_rows`` — never a [V, D] buffer. A dense
    gradient (sparse+dense fan-in densified by the sum op) falls back to
    the dense formula."""
    p = single(ins, "Param")
    g = single(ins, "Grad")
    lr = single(ins, "LearningRate").astype(p.dtype).reshape(())
    if not isinstance(g, SelectedRows):
        return out(ParamOut=p - lr * g.astype(p.dtype))
    m = g.merged()  # unique ids + segment-sum of duplicate rows
    step = -(lr * m.values.astype(p.dtype))
    mesh = _vocab_mesh(attrs, p.shape[0])
    if mesh is not None:
        from ..parallel.sharded_embedding import vp_scatter_add

        return out(ParamOut=vp_scatter_add(
            p, m.rows, step, mesh,
            vocab_axis=attrs.get("vocab_axis", "mp")))
    return out(ParamOut=p.at[m.rows].add(step, mode="drop"))


@register_op("sparse_adagrad")
def sparse_adagrad(attrs, ins):
    """Row-sparse adagrad (adagrad_op.cc SelectedRows kernel): the
    moment accumulates and the parameter steps only on touched rows —
    both scatters row-granular, both bitwise the dense formula on the
    rows they touch."""
    p = single(ins, "Param")
    g = single(ins, "Grad")
    mom = single(ins, "Moment")
    lr = single(ins, "LearningRate").reshape(())
    eps = attrs.get("epsilon", 1e-6)
    if not isinstance(g, SelectedRows):
        g = g.astype(jnp.float32)
        mom_out = mom + jnp.square(g)
        p_out = p - (lr * g / (jnp.sqrt(mom_out) + eps)).astype(p.dtype)
        return {"ParamOut": [p_out], "MomentOut": [mom_out]}
    m = g.merged()
    gv = m.values.astype(jnp.float32)
    mesh = _vocab_mesh(attrs, p.shape[0])
    if mesh is not None:
        from ..parallel.sharded_embedding import (vp_rows_pull,
                                                  vp_scatter_add)

        axis = attrs.get("vocab_axis", "mp")
        mom_rows = vp_rows_pull(mom, m.rows, mesh, vocab_axis=axis) \
            + jnp.square(gv)
        step = (lr * gv / (jnp.sqrt(mom_rows) + eps)).astype(p.dtype)
        return {"ParamOut": [vp_scatter_add(p, m.rows, -step, mesh,
                                            vocab_axis=axis)],
                "MomentOut": [vp_scatter_add(mom, m.rows, mom_rows, mesh,
                                             vocab_axis=axis,
                                             mode="set")]}
    mom_rows = mom[m.rows] + jnp.square(gv)
    step = (lr * gv / (jnp.sqrt(mom_rows) + eps)).astype(p.dtype)
    return {"ParamOut": [p.at[m.rows].add(-step, mode="drop")],
            "MomentOut": [mom.at[m.rows].set(mom_rows, mode="drop")]}

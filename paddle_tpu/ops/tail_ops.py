"""Tail of the v1 layer zoo: the last reference layers with no
equivalent under any repo name (VERDICT r4 Missing #3). Each op cites
its reference implementation; all are XLA-vectorized reformulations of
per-row CPU/GPU loops.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op
from .common import maybe, out, single
from .sequence_ops import time_mask


@register_op("sub_seq", optional_inputs=("Length",))
def sub_seq(attrs, ins):
    """Per-row sub-sequence slice (reference gserver SubSequenceLayer.cpp:
    row b of the output is x[b, offset[b] : offset[b]+size[b]]). Dense
    form: gather along time with an arange + offset index, masked past
    each row's size; OutLength carries the new lengths."""
    x = single(ins, "X")            # [b, T, d]
    offsets = single(ins, "Offsets").reshape(-1).astype(jnp.int32)
    sizes = single(ins, "Sizes").reshape(-1).astype(jnp.int32)
    b, T = x.shape[0], x.shape[1]
    idx = offsets[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]
    idx = jnp.clip(idx, 0, T - 1)
    gathered = jnp.take_along_axis(
        x, idx.reshape(b, T, *([1] * (x.ndim - 2))), axis=1)
    mask = time_mask(sizes, T, x.dtype)
    gathered = gathered * mask.reshape(b, T, *([1] * (x.ndim - 2)))
    return out(Out=gathered, OutLength=sizes)


@register_op("switch_order")
def switch_order(attrs, ins):
    """NCHW -> NHWC dimension switch (reference SwitchOrderLayer /
    function/SwitchOp.cpp). ``reshape_axis`` splits the switched dims
    into a 2-D [prod(dims[:axis]), prod(dims[axis:])] view per batch row
    when given (the reference's reshape contract)."""
    x = single(ins, "X")  # [b, C, H, W]
    y = jnp.transpose(x, (0, 2, 3, 1))
    axis = int(attrs.get("reshape_axis", 0) or 0)
    if axis:
        b = y.shape[0]
        dims = y.shape[1:]
        lead = 1
        for d in dims[:axis]:
            lead *= d
        y = y.reshape(b, lead, -1)
    return out(Out=y)


@register_op("scale_sub_region")
def scale_sub_region(attrs, ins):
    """Multiply a per-sample sub-region of an NCHW tensor by ``value``
    (reference function/ScaleSubRegionOp.cpp). Indices [b, 6] are
    1-based inclusive (cstart, cend, hstart, hend, wstart, wend)."""
    x = single(ins, "X")  # [b, C, H, W]
    idx = single(ins, "Indices").astype(jnp.int32)  # [b, 6]
    value = attrs.get("value", 1.0)
    b, C, H, W = x.shape

    def rng_mask(n, lo, hi):
        ar = jnp.arange(n, dtype=jnp.int32)
        return ((ar[None, :] >= lo[:, None] - 1)
                & (ar[None, :] <= hi[:, None] - 1))

    m = (rng_mask(C, idx[:, 0], idx[:, 1])[:, :, None, None]
         & rng_mask(H, idx[:, 2], idx[:, 3])[:, None, :, None]
         & rng_mask(W, idx[:, 4], idx[:, 5])[:, None, None, :])
    return out(Out=jnp.where(m, x * value, x))


@register_op("lambda_cost", optional_inputs=("Length",))
def lambda_cost(attrs, ins):
    """LambdaRank listwise cost (reference gserver CostLayer LambdaCost):
    per list, sum over item pairs (i, j) with rel_i > rel_j of
    |dNDCG_ij| * log(1 + exp(-(s_i - s_j))) — the differentiable
    surrogate whose gradient is the lambda the reference computes
    directly. NDCG truncated at ``NDCG_num``; with ``max_sort_size`` set
    (>0) only pairs whose HIGHER-relevance anchor ranks inside the top
    ``max_sort_size`` items contribute (LambdaCost::calcGrad iterates
    anchors over the truncated sort only — the partner may rank
    anywhere)."""
    score = single(ins, "Score")    # [b, T] model scores
    rel = single(ins, "Label")      # [b, T] relevance
    lengths = maybe(ins, "Length")
    ndcg_num = int(attrs.get("NDCG_num", 5))
    max_sort = int(attrs.get("max_sort_size", -1))
    b, T = score.shape
    valid = (time_mask(lengths, T, jnp.float32) if lengths is not None
             else jnp.ones((b, T), jnp.float32))
    relf = rel.astype(jnp.float32) * valid
    # ideal DCG from the top-NDCG_num relevances per row
    k = min(ndcg_num, T)
    top_rel = jax.lax.top_k(relf, k)[0]
    disc = 1.0 / jnp.log2(jnp.arange(k, dtype=jnp.float32) + 2.0)
    idcg = jnp.sum((jnp.exp2(top_rel) - 1.0) * disc[None, :], axis=1)
    idcg = jnp.maximum(idcg, 1e-6)
    # rank of each item by current score (descending, within valid rows)
    neg = jnp.where(valid > 0, score.astype(jnp.float32), -jnp.inf)
    order = jnp.argsort(-neg, axis=1)
    rank = jnp.argsort(order, axis=1).astype(jnp.float32)  # 0-based
    gain = jnp.exp2(relf) - 1.0
    d = 1.0 / jnp.log2(rank + 2.0)
    d = jnp.where(rank < ndcg_num, d, 0.0)
    # |delta NDCG| of swapping i and j
    dg = gain[:, :, None] - gain[:, None, :]
    dd = d[:, :, None] - d[:, None, :]
    delta = jnp.abs(dg * dd) / idcg[:, None, None]
    sdiff = score[:, :, None] - score[:, None, :]
    pairloss = jnp.logaddexp(0.0, -sdiff.astype(jnp.float32))
    pair_valid = (valid[:, :, None] * valid[:, None, :]
                  * (relf[:, :, None] > relf[:, None, :]))
    if max_sort > 0:
        # the reference's truncated-sort mode: the ANCHOR (the higher-
        # relevance member, axis 1 here since pair_valid keeps rel_i >
        # rel_j) must rank inside the top max_sort_size items; the
        # partner j may rank anywhere (LambdaCost::calcGrad's outer loop
        # runs over the truncated sort, the inner over the full list)
        in_top = (rank < max_sort).astype(jnp.float32)
        pair_valid = pair_valid * in_top[:, :, None]
    cost = jnp.sum(delta * pairloss * pair_valid, axis=(1, 2))
    return out(Out=cost.reshape(b, 1))


@register_op("sub_nested_seq")
def sub_nested_seq(attrs, ins):
    """Select sub-sequences from a nested sequence (reference
    SubNestedSequenceLayer.cpp). Dense form: X [b, S, T, d] (the
    lod_level=2 plane), Indices [b, K] sub-sequence ids per row ->
    Out [b, K, T, d] (gather along the sub-sequence axis; negative
    ids select nothing and zero the slot)."""
    x = single(ins, "X")
    idx = single(ins, "Indices").astype(jnp.int32)
    b, S = x.shape[0], x.shape[1]
    k = idx.shape[1]
    safe = jnp.clip(idx, 0, S - 1)
    expand = safe.reshape(b, k, *([1] * (x.ndim - 2)))
    gathered = jnp.take_along_axis(
        x, jnp.broadcast_to(expand, (b, k) + x.shape[2:]), axis=1)
    valid = (idx >= 0).reshape(b, k, *([1] * (x.ndim - 2)))
    return out(Out=gathered * valid.astype(x.dtype))


@register_op("tensor_product")
def tensor_product(attrs, ins):
    """Bilinear tensor product (reference gserver TensorLayer.cpp):
    out[b, i] = a[b] @ W[i] @ b[b]^T, W [size, da, db] — one einsum,
    MXU-shaped."""
    a = single(ins, "A")
    b2 = single(ins, "B")
    w = single(ins, "Weight")
    return out(Out=jnp.einsum("bm,imn,bn->bi", a, w, b2))


@register_op("cross_entropy_with_selfnorm")
def cross_entropy_with_selfnorm(attrs, ins):
    """CE over softmax OUTPUT probs plus the self-normalization penalty
    (reference CostLayer.cpp:113 MultiClassCrossEntropyWithSelfNorm):
    cost = -log(p[label]) + log(Z) + alpha * log(Z)^2 with Z the row sum
    of the input (drives Z -> 1 so unnormalized serving can skip the
    softmax denominator — the NCE-era trick)."""
    x = single(ins, "X")            # [b, C] softmax probs
    label = single(ins, "Label").reshape(-1)
    alpha = attrs.get("softmax_selfnorm_alpha", 0.1)
    xf = x.astype(jnp.float32)
    z = jnp.sum(xf, axis=1)
    logz = jnp.log(jnp.maximum(z, 1e-20))
    p = jnp.take_along_axis(xf, label[:, None].astype(jnp.int32),
                            axis=1)[:, 0]
    ce = -jnp.log(jnp.maximum(p, 1e-20))
    return out(Out=(ce + logz + alpha * logz * logz).reshape(-1, 1))

"""SSD detection stack + hierarchical sigmoid.

TPU-native equivalents of the reference's detection layers
(/root/reference/paddle/gserver/layers/PriorBox.cpp, MultiBoxLossLayer.cpp
+ DetectionUtil.cpp, DetectionOutputLayer.cpp — the last already exists as
the ``detection_output`` op) and HierarchicalSigmoidLayer.cpp. All dense,
batch-padded, loop-free formulations: matching/mining become argmax/top_k
over [P, G] IoU tables instead of per-box host loops.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op
from .common import maybe, out, single


@register_op("prior_box")
def prior_box(attrs, ins):
    """SSD anchor generation (PriorBox.cpp:79-131): for every feature-map
    cell, emit one box per min_size, one sqrt(min*max) box per max_size,
    and one box per extra aspect ratio (input ratios are flipped r, 1/r as
    in init :68-74), all center-aligned on the cell, normalized by image
    size, optionally clipped. Outputs Boxes [H, W, num_priors, 4]
    (xmin, ymin, xmax, ymax) and Variances broadcast to the same shape.

    Inputs are the feature map [b, H, W, C] and image [b, h, w, 3] (only
    shapes are read — matching the reference, which reads frame sizes).
    """
    feat = single(ins, "Input")
    image = single(ins, "Image")
    fh, fw = feat.shape[1], feat.shape[2]
    ih, iw = image.shape[1], image.shape[2]
    from ..core.enforce import enforce, enforce_eq

    min_sizes = [float(s) for s in attrs["min_sizes"]]
    max_sizes = [float(s) for s in attrs.get("max_sizes", [])]
    if max_sizes:
        enforce_eq(len(min_sizes), len(max_sizes),
                   "prior_box: min_sizes and max_sizes lengths")
    variance = [float(v) for v in attrs.get("variances",
                                            [0.1, 0.1, 0.2, 0.2])]
    # ratio 1 is the min-size box itself; the reference skips it in the
    # generation loop (PriorBox.cpp forward: fabs(ar - 1.) < 1e-6 continue)
    flip_ratios = []
    for r in attrs.get("aspect_ratios", []):
        if abs(float(r) - 1.0) < 1e-6:
            continue
        flip_ratios += [float(r), 1.0 / float(r)]
    enforce(min_sizes, "prior_box: min_sizes must be non-empty")
    clip = attrs.get("clip", False)

    step_w, step_h = iw / fw, ih / fh
    cx = (jnp.arange(fw, dtype=jnp.float32) + 0.5) * step_w  # [W]
    cy = (jnp.arange(fh, dtype=jnp.float32) + 0.5) * step_h  # [H]
    cx = jnp.broadcast_to(cx[None, :], (fh, fw))
    cy = jnp.broadcast_to(cy[:, None], (fh, fw))

    widths, heights = [], []
    for i, ms in enumerate(min_sizes):
        widths.append(ms)
        heights.append(ms)
        if max_sizes:
            s = (ms * max_sizes[i]) ** 0.5
            widths.append(s)
            heights.append(s)
        for r in flip_ratios:
            widths.append(ms * (r ** 0.5))
            heights.append(ms / (r ** 0.5))
    w_arr = jnp.asarray(widths, jnp.float32)   # [np]
    h_arr = jnp.asarray(heights, jnp.float32)

    xmin = (cx[..., None] - w_arr / 2) / iw
    ymin = (cy[..., None] - h_arr / 2) / ih
    xmax = (cx[..., None] + w_arr / 2) / iw
    ymax = (cy[..., None] + h_arr / 2) / ih
    boxes = jnp.stack([xmin, ymin, xmax, ymax], axis=-1)  # [H, W, np, 4]
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32), boxes.shape)
    return out(Boxes=boxes, Variances=var)


def _iou_table(a, b):
    """[N, 4] x [M, 4] -> [N, M] IoU (DetectionUtil.cpp jaccardOverlap)."""
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = ((a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1]))[:, None]
    area_b = ((b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1]))[None, :]
    return inter / jnp.maximum(area_a + area_b - inter, 1e-10)


@register_op("iou_similarity")
def iou_similarity(attrs, ins):
    """Pairwise IoU table; batched X [b, N, 4] or flat [N, 4]."""
    x = single(ins, "X")
    y = single(ins, "Y")
    if x.ndim == 3:
        return out(Out=jax.vmap(_iou_table)(x, jnp.broadcast_to(
            y if y.ndim == 3 else y[None], (x.shape[0],) + tuple(y.shape[-2:]))))
    return out(Out=_iou_table(x, y))


def _encode(gt, prior, var):
    """SSD box encoding (DetectionUtil.cpp encodeBBoxWithVar)."""
    pw = prior[..., 2] - prior[..., 0]
    ph = prior[..., 3] - prior[..., 1]
    pcx = (prior[..., 0] + prior[..., 2]) / 2
    pcy = (prior[..., 1] + prior[..., 3]) / 2
    gw = jnp.maximum(gt[..., 2] - gt[..., 0], 1e-10)
    gh = jnp.maximum(gt[..., 3] - gt[..., 1], 1e-10)
    gcx = (gt[..., 0] + gt[..., 2]) / 2
    gcy = (gt[..., 1] + gt[..., 3]) / 2
    return jnp.stack([
        (gcx - pcx) / pw / var[..., 0],
        (gcy - pcy) / ph / var[..., 1],
        jnp.log(gw / pw) / var[..., 2],
        jnp.log(gh / ph) / var[..., 3],
    ], axis=-1)


def _decode(code, prior, var):
    pw = prior[..., 2] - prior[..., 0]
    ph = prior[..., 3] - prior[..., 1]
    pcx = (prior[..., 0] + prior[..., 2]) / 2
    pcy = (prior[..., 1] + prior[..., 3]) / 2
    cx = code[..., 0] * var[..., 0] * pw + pcx
    cy = code[..., 1] * var[..., 1] * ph + pcy
    w = jnp.exp(code[..., 2] * var[..., 2]) * pw
    h = jnp.exp(code[..., 3] * var[..., 3]) * ph
    return jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                     axis=-1)


@register_op("box_coder", optional_inputs=("Variance",))
def box_coder(attrs, ins):
    """Encode target boxes against priors, or decode predicted offsets
    (DetectionUtil encode/decodeBBoxWithVar). ``code_type``:
    'encode_center_size' | 'decode_center_size'."""
    target = single(ins, "TargetBox")
    prior = single(ins, "PriorBox")
    var = maybe(ins, "Variance")
    if var is None:
        var = jnp.ones_like(prior)
    code_type = attrs.get("code_type", "encode_center_size")
    if prior.ndim < target.ndim:  # broadcast priors over the batch
        prior = jnp.broadcast_to(prior[None], target.shape)
        var = jnp.broadcast_to(var[None] if var.ndim < target.ndim else var,
                               target.shape)
    if code_type == "encode_center_size":
        return out(OutputBox=_encode(target, prior, var))
    return out(OutputBox=_decode(target, prior, var))


@register_op("multibox_loss", optional_inputs=("GtLength",))
def multibox_loss(attrs, ins):
    """SSD training loss (MultiBoxLossLayer.cpp): smooth-L1 location loss
    on matched priors + softmax confidence loss with hard negative mining.

    Dense formulation: per image, the [P, G] IoU table gives per-prior
    best-gt matches (IoU >= overlap_threshold) plus the bipartite
    per-gt-best-prior overrides (DetectionUtil matchBBox); negatives are
    the neg_pos_ratio * num_pos highest-confidence-loss unmatched priors,
    selected with top_k instead of the reference's sort (:FindMatches /
    :MineHardExamples).

    Normalization matches the reference's cost contract
    (MultiBoxLossLayer.cpp:206,258 — batch-summed loss / BATCH-WIDE match
    count): Loss is [b, 1] with out[i] = raw_i * b / total_matches, so
    ``mean(Loss)`` equals the reference's scalar cost and every matched
    prior carries equal gradient weight regardless of which image it
    belongs to.

    Inputs: PriorBoxes [P, 4], PriorVariances [P, 4], LocPred [b, P, 4],
    ConfPred [b, P, C] (class 0 = background), GtBoxes [b, G, 4],
    GtClasses [b, G] (1..C-1), GtLength [b].
    """
    priors = single(ins, "PriorBoxes")
    pvar = single(ins, "PriorVariances")
    loc = single(ins, "LocPred")
    conf = single(ins, "ConfPred")
    gt_boxes = single(ins, "GtBoxes")
    gt_cls = single(ins, "GtClasses")
    b, P = loc.shape[0], loc.shape[1]
    G = gt_boxes.shape[1]
    gt_cls = gt_cls.reshape(b, G).astype(jnp.int32)
    glen = maybe(ins, "GtLength")
    if glen is None:
        glen = jnp.full((b,), G, jnp.int32)
    glen = glen.reshape(-1).astype(jnp.int32)
    thresh = float(attrs.get("overlap_threshold", 0.5))
    neg_ratio = float(attrs.get("neg_pos_ratio", 3.0))

    def one_image(loc_p, conf_p, gtb, gtc, n_gt):
        gmask = jnp.arange(G) < n_gt                     # [G]
        iou = _iou_table(priors, gtb)                    # [P, G]
        iou = jnp.where(gmask[None, :], iou, -1.0)
        # per-prior best gt
        best_gt = jnp.argmax(iou, axis=1)                # [P]
        best_iou = jnp.take_along_axis(iou, best_gt[:, None],
                                       axis=1)[:, 0]
        matched = best_iou >= thresh
        # bipartite overrides: each gt claims its best prior
        best_prior = jnp.argmax(iou, axis=0)             # [G]
        matched = matched.at[best_prior].set(
            jnp.where(gmask, True, matched[best_prior]))
        best_gt = best_gt.at[best_prior].set(
            jnp.where(gmask, jnp.arange(G), best_gt[best_prior]))
        n_pos = jnp.sum(matched)

        # location loss: smooth L1 on matched priors
        target = _encode(gtb[best_gt], priors, pvar)     # [P, 4]
        d = loc_p - target
        ad = jnp.abs(d)
        sl1 = jnp.where(ad < 1.0, 0.5 * d * d, ad - 0.5).sum(-1)
        loc_loss = jnp.where(matched, sl1, 0.0).sum()

        # confidence loss: softmax CE against matched class / background
        tgt_cls = jnp.where(matched, gtc[best_gt], 0)    # [P]
        logp = jax.nn.log_softmax(conf_p, axis=-1)
        ce = -jnp.take_along_axis(logp, tgt_cls[:, None], axis=1)[:, 0]
        pos_conf = jnp.where(matched, ce, 0.0).sum()
        # hard negative mining: top (neg_ratio * n_pos) bg-loss priors
        bg_ce = -logp[:, 0]
        neg_cand = jnp.where(matched, -jnp.inf, bg_ce)
        order = jnp.argsort(-neg_cand)                   # desc
        rank = jnp.zeros((P,), jnp.int32).at[order].set(jnp.arange(P))
        n_neg = jnp.minimum((neg_ratio * n_pos).astype(jnp.int32),
                            P - n_pos)
        neg_sel = (~matched) & (rank < n_neg)
        neg_conf = jnp.where(neg_sel, ce, 0.0).sum()

        return loc_loss + pos_conf + neg_conf, n_pos

    raw, n_pos = jax.vmap(one_image)(loc, conf, gt_boxes, gt_cls, glen)
    total = jnp.maximum(jnp.sum(n_pos).astype(raw.dtype), 1.0)
    loss = raw * (b / total)
    return out(Loss=loss[:, None])


@register_op("bilinear_interp")
def bilinear_interp(attrs, ins):
    """Bilinear resize of NHWC feature maps (BilinearInterpLayer.cpp):
    ALIGN-CORNERS convention — ratio = (in-1)/(out-1) when out > 1 —
    exactly the gserver layer's sampling grid."""
    x = single(ins, "X")
    oh = int(attrs["out_h"])
    ow = int(attrs["out_w"])
    b, ih, iw, c = x.shape
    ry = (ih - 1.0) / (oh - 1.0) if oh > 1 else 0.0
    rx = (iw - 1.0) / (ow - 1.0) if ow > 1 else 0.0
    yy = jnp.arange(oh, dtype=jnp.float32) * ry
    xx = jnp.arange(ow, dtype=jnp.float32) * rx
    y0 = jnp.clip(jnp.floor(yy).astype(jnp.int32), 0, ih - 1)
    x0 = jnp.clip(jnp.floor(xx).astype(jnp.int32), 0, iw - 1)
    y1 = jnp.minimum(y0 + 1, ih - 1)
    x1 = jnp.minimum(x0 + 1, iw - 1)
    wy = (yy - y0.astype(jnp.float32))[None, :, None, None]
    wx = (xx - x0.astype(jnp.float32))[None, None, :, None]
    p00 = x[:, y0][:, :, x0]
    p01 = x[:, y0][:, :, x1]
    p10 = x[:, y1][:, :, x0]
    p11 = x[:, y1][:, :, x1]
    top = p00 * (1 - wx) + p01 * wx
    bot = p10 * (1 - wx) + p11 * wx
    return out(Out=top * (1 - wy) + bot * wy)


@register_op("hsigmoid", optional_inputs=("Bias",))
def hsigmoid(attrs, ins):
    """Hierarchical sigmoid loss over a complete binary tree of classes
    (HierarchicalSigmoidLayer.cpp; paddle/math MatrixBits codes): for a
    sample with label c, walk the implicit tree node sequence of
    ``code = c + num_classes`` from the bit below the leading 1 downward;
    at depth j the internal node index is code >> (j+1) minus 1... —
    equivalently, the reference's SimpleCode: node_j = (code >> (j+1)) - 1
    with bit_j = (code >> j) & 1. Loss = sum_j softplus(-(sign_j) * (x .
    w_node_j + b_node_j)) with sign_j = 2*bit_j - 1, i.e. the standard
    log-sigmoid path loss. W is [num_classes-1, d]; Out is [b, 1].
    """
    x = single(ins, "X")                  # [b, d]
    w = single(ins, "W")                  # [num_classes-1, d]
    label = single(ins, "Label").reshape(-1).astype(jnp.int32)
    bias = maybe(ins, "Bias")
    num_classes = int(attrs["num_classes"])
    max_depth = max(1, (num_classes - 1).bit_length())

    code = label + num_classes            # [b]
    js = jnp.arange(max_depth, dtype=jnp.int32)          # [D]
    node = (code[:, None] >> (js[None, :] + 1)) - 1      # [b, D]
    # level j is on the path iff its node index exists (bits below the
    # leading 1): code >> (j+1) >= 1 <=> j <= bit_length(code) - 2
    active = node >= 0                                   # [b, D]
    bit = (code[:, None] >> js[None, :]) & 1             # [b, D]
    node_c = jnp.clip(node, 0, num_classes - 2)
    wj = w[node_c]                                       # [b, D, d]
    logits = jnp.einsum("bd,bjd->bj", x, wj)
    if bias is not None:
        logits = logits + bias.reshape(-1)[node_c]
    sign = 2.0 * bit.astype(logits.dtype) - 1.0
    losses = jax.nn.softplus(-sign * logits)             # [b, D]
    return out(Out=jnp.where(active, losses, 0.0).sum(-1, keepdims=True))
